"""Fault-injection matrix: the streamed multi-chip pipeline must
survive the failures it will actually see.

The 8 virtual CPU devices (tests/conftest.py) stand in for an 8-chip
topology.  Every recovery path — transient dispatch retry, permanent
fault -> eviction -> survivor replay, last-device loss -> host backend,
hung-fetch deadline, killed-mid-write crash consistency — must leave
the output **bit-identical** to a fault-free single-chip run: the
barrier merges are window-ordered and the device/host kernels are
bit-parity twins, so recovery changes where work runs, never what it
computes.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from adam_tpu.parallel import device_pool as dp
from adam_tpu.utils import faults
from adam_tpu.utils import retry as retry_mod
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Every test starts and ends with faults disarmed, fast retry
    backoff, and the global tracer untouched."""
    os.environ["ADAM_TPU_RETRY_BACKOFF_S"] = "0.001"
    was_recording = tele.TRACE.recording
    yield
    faults.clear()
    os.environ.pop("ADAM_TPU_RETRY_BACKOFF_S", None)
    tele.TRACE.recording = was_recording


# ---------------------------------------------------------------------------
# Fault-spec grammar + point mechanics
# ---------------------------------------------------------------------------
def test_fault_spec_parse_and_validation():
    cs = faults.parse_spec(
        "device.dispatch=transient,every=3;"
        "device.dispatch=permanent,device=1,times=1;"
        "device.fetch=delay:2.5,after=4;"
        "parquet.write=transient,p=0.5,seed=7"
    )
    assert [c.site for c in cs] == [
        "device.dispatch", "device.dispatch", "device.fetch",
        "parquet.write",
    ]
    assert cs[0].every == 3 and cs[0].action == "transient"
    assert cs[1].device == "1" and cs[1].times == 1
    assert cs[2].action == "delay" and cs[2].delay_s == 2.5
    assert cs[3].p == 0.5
    for bad in (
        "nope.site=transient",       # unknown point
        "device.dispatch=explode",   # unknown action
        "device.dispatch",           # missing action
        "device.dispatch=transient,every=zero",  # bad option value
        "device.dispatch=transient,wat=1",       # unknown option
        "device.dispatch=delay:soon",            # bad delay
    ):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_kill_action_parses_and_sigkills_self(monkeypatch):
    """The ``kill`` action (the chaos harness's weapon at the
    ``proc.kill`` site) parses and delivers SIGKILL to the process
    itself — captured here instead of actually dying."""
    (c,) = faults.parse_spec("proc.kill=kill,device=pass_c,after=1,times=1")
    assert c.site == "proc.kill" and c.action == "kill"
    assert c.device == "pass_c" and c.after == 1 and c.times == 1
    sent = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: sent.append((pid, sig)))
    faults.install("proc.kill=kill,device=pass_a,after=1,times=1")
    faults.point("proc.kill", device="ingest")   # wrong phase: no count
    faults.point("proc.kill", device="pass_a")   # arrival 1: after=1 skips
    assert sent == []
    faults.point("proc.kill", device="pass_a")   # arrival 2: fires
    assert sent == [(os.getpid(), signal.SIGKILL)]
    faults.point("proc.kill", device="pass_a")   # times=1 spent
    assert len(sent) == 1
    with pytest.raises(ValueError):
        faults.parse_spec("device.dispatch=kill:9")  # kill takes no arg


def test_point_disabled_is_noop_and_deterministic_when_armed():
    faults.clear()
    faults.point("device.dispatch")  # disarmed: must do nothing
    faults.install("device.dispatch=transient,every=3,times=2")
    fired = []
    for i in range(12):
        try:
            faults.point("device.dispatch")
            fired.append(False)
        except faults.TransientFault:
            fired.append(True)
    # arrivals 3 and 6 fire; times=2 silences 9 and 12
    assert [i + 1 for i, f in enumerate(fired) if f] == [3, 6]
    # device filter: non-matching attributions don't advance the clause
    faults.install("device.dispatch=permanent,device=5")
    faults.point("device.dispatch", device=3)
    with pytest.raises(faults.PermanentFault):
        faults.point("device.dispatch", device=5)


def test_same_site_clauses_all_count_arrivals():
    """Every clause on a site sees every arrival — an earlier clause
    firing must not make later clauses' every/after schedules drift
    from real arrival counts (the documented 'Nth time any call
    reaches this site' semantics)."""
    faults.install(
        "device.dispatch=transient,every=2;"
        "device.dispatch=permanent,after=5"
    )
    kinds = []
    for _ in range(6):
        try:
            faults.point("device.dispatch")
            kinds.append("-")
        except faults.TransientFault:
            kinds.append("T")
        except faults.PermanentFault:
            kinds.append("P")
    # arrivals 2/4/6 match clause 1; arrival 6 ALSO passes clause 2's
    # after=5, but the first matching clause wins — and clause 2 saw
    # all 6 arrivals, so arrival 7 (odd, > 5) fires it
    assert kinds == ["-", "T", "-", "T", "-", "T"]
    with pytest.raises(faults.PermanentFault):
        faults.point("device.dispatch")


def test_xla_runtime_error_retryability_by_status():
    """Only transient XLA statuses retry; deterministic device errors
    (OOM, bad argument) must surface to the eviction path on first
    sight instead of burning the retry budget."""

    class XlaRuntimeError(Exception):
        pass

    assert retry_mod.is_retryable(
        XlaRuntimeError("UNAVAILABLE: connection reset by tunnel")
    )
    assert retry_mod.is_retryable(
        XlaRuntimeError("INTERNAL: RPC stream closed")
    )
    assert not retry_mod.is_retryable(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
    )
    assert not retry_mod.is_retryable(
        XlaRuntimeError("INVALID_ARGUMENT: shape mismatch")
    )


def test_seeded_probabilistic_clause_reproduces():
    def run():
        faults.install("device.dispatch=transient,p=0.4,seed=42")
        out = []
        for _ in range(20):
            try:
                faults.point("device.dispatch")
                out.append(0)
            except faults.TransientFault:
                out.append(1)
        return out

    a, b = run(), run()
    assert a == b and 1 in a and 0 in a


# ---------------------------------------------------------------------------
# Retry / deadline primitives
# ---------------------------------------------------------------------------
def test_retry_call_transient_then_success():
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise faults.TransientFault("flaky")
        return "ok"

    policy = retry_mod.RetryPolicy(attempts=3, backoff_s=0.0)
    assert retry_mod.retry_call(flaky, site="t", policy=policy) == "ok"
    assert calls[0] == 3


def test_retry_call_permanent_not_retried_and_budget_exhausts():
    calls = [0]

    def dead():
        calls[0] += 1
        raise faults.PermanentFault("dead chip")

    policy = retry_mod.RetryPolicy(attempts=5, backoff_s=0.0)
    with pytest.raises(faults.PermanentFault):
        retry_mod.retry_call(dead, site="t", policy=policy)
    assert calls[0] == 1  # permanent: exactly one attempt

    calls[0] = 0

    def always_transient():
        calls[0] += 1
        raise faults.TransientFault("still down")

    with pytest.raises(faults.TransientFault):
        retry_mod.retry_call(always_transient, site="t", policy=policy)
    assert calls[0] == 5  # the full budget, then the original error


def test_retry_jitter_deterministic_per_site():
    """ISSUE-10 satellite: seeded, per-site deterministic backoff
    jitter — a pure function of (seed, site, attempt), so a fixed seed
    reproduces the exact sleep schedule while sites decorrelate; the
    default stays jitter-free (factor exactly 1.0)."""
    # default: off
    assert retry_mod.jitter_factor("a", 1) == 1.0
    assert retry_mod.jitter_factor("a", 1, seed=7, amount=0.0) == 1.0
    # deterministic: same (seed, site, attempt) -> same factor
    f1 = retry_mod.jitter_factor("device.fetch", 1, seed=7, amount=0.5)
    f2 = retry_mod.jitter_factor("device.fetch", 1, seed=7, amount=0.5)
    assert f1 == f2 and 1.0 <= f1 < 1.5
    # decorrelated: different sites / seeds / attempts differ
    others = {
        retry_mod.jitter_factor("device.dispatch", 1, seed=7, amount=0.5),
        retry_mod.jitter_factor("device.fetch", 2, seed=7, amount=0.5),
        retry_mod.jitter_factor("device.fetch", 1, seed=8, amount=0.5),
    }
    assert f1 not in others and len(others) == 3


def test_retry_jitter_sleeps_scaled_and_decisions_unchanged(monkeypatch):
    """Jitter stretches the SLEEP only: attempt counts and outcomes
    are identical to the jitter-free run, and the slept durations are
    exactly backoff * jitter_factor for the fixed seed."""
    sleeps = []
    monkeypatch.setattr(retry_mod.time, "sleep", sleeps.append)
    calls = [0]

    def always_transient():
        calls[0] += 1
        raise faults.TransientFault("down")

    policy = retry_mod.RetryPolicy(attempts=3, backoff_s=0.1,
                                   jitter=0.5, jitter_seed=7)
    with pytest.raises(faults.TransientFault):
        retry_mod.retry_call(always_transient, site="s", policy=policy)
    assert calls[0] == 3  # same decisions as jitter-free
    expected = [
        0.1 * retry_mod.jitter_factor("s", 1, seed=7, amount=0.5),
        0.2 * retry_mod.jitter_factor("s", 2, seed=7, amount=0.5),
    ]
    assert sleeps == pytest.approx(expected)
    # and the whole schedule reproduces for the same seed
    sleeps2 = []
    monkeypatch.setattr(retry_mod.time, "sleep", sleeps2.append)
    calls[0] = 0
    with pytest.raises(faults.TransientFault):
        retry_mod.retry_call(always_transient, site="s", policy=policy)
    assert sleeps2 == sleeps


def test_retry_jitter_env_knobs(monkeypatch):
    monkeypatch.setenv("ADAM_TPU_RETRY_JITTER", "0.25")
    monkeypatch.setenv("ADAM_TPU_RETRY_JITTER_SEED", "42")
    p = retry_mod.RetryPolicy.from_env()
    assert p.jitter == 0.25 and p.jitter_seed == 42
    monkeypatch.setenv("ADAM_TPU_RETRY_JITTER", "nope")
    monkeypatch.setenv("ADAM_TPU_RETRY_JITTER_SEED", "also-nope")
    p = retry_mod.RetryPolicy.from_env()  # typo degrades to default
    assert p.jitter == 0.0 and p.jitter_seed == 0


def test_call_with_deadline_timeout_and_passthrough():
    assert retry_mod.call_with_deadline(lambda: 7, 5.0, site="t") == 7
    with pytest.raises(retry_mod.DeadlineExceeded):
        retry_mod.call_with_deadline(
            lambda: time.sleep(3), 0.05, site="t"
        )
    with pytest.raises(ZeroDivisionError):  # worker errors relay as-is
        retry_mod.call_with_deadline(lambda: 1 / 0, 5.0, site="t")
    assert retry_mod.is_retryable(retry_mod.DeadlineExceeded("x"))


def test_transfer_thread_floor_independent_of_affinity(monkeypatch):
    """ROADMAP satellite: chunked fetch overlap is GIL-released RPC
    wait — the pool must keep >= 2 I/O threads even on a 1-core
    affinity mask."""
    from adam_tpu.utils import transfer

    monkeypatch.setattr(os, "sched_getaffinity", lambda pid: {0},
                        raising=False)
    assert transfer._max_threads() == 2


# ---------------------------------------------------------------------------
# DevicePool eviction unit behavior
# ---------------------------------------------------------------------------
def test_pool_eviction_round_robin_and_exhaustion():
    pool = dp.DevicePool(limit=4)
    tr = tele.Tracer(recording=True)
    assert [pool.device_index(i) for i in range(4)] == [0, 1, 2, 3]
    assert pool.evict(pool.devices[1], reason="test", tracer=tr)
    assert not pool.evict(pool.devices[1], tracer=tr)  # already dead
    assert pool.evict(None) is False                   # nothing to evict
    # survivors round-robin; indices still name ORIGINAL pool slots
    assert len(pool.alive_devices()) == 3
    assert [pool.device_index(i) for i in range(4)] == [0, 2, 3, 0]
    assert pool.n == 4  # the configured fan-out does not shrink
    assert tr.snapshot()["counters"][tele.C_DEVICE_EVICTED] == 1
    for d in pool.alive_devices():
        pool.evict(d, tracer=tr)
    with pytest.raises(dp.AllDevicesEvicted):
        pool.device(0)
    assert tr.snapshot()["counters"][tele.C_DEVICE_EVICTED] == 4


def test_prewarm_skips_evicted_devices():
    dp.reset_prewarm_cache()
    try:
        pool = dp.DevicePool(limit=3)
        pool.evict(pool.devices[2], reason="test")
        seen = []
        entries = [(("k", 1), lambda dev: seen.append(dev.id))]
        assert pool.prewarm(entries) == 2
        assert sorted(seen) == [0, 1]
    finally:
        dp.reset_prewarm_cache()


# ---------------------------------------------------------------------------
# Crash-consistent writes + writer-pool error propagation
# ---------------------------------------------------------------------------
def _tiny_dataset():
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io.sam import SamHeader

    recs = [
        dict(name=f"r{i}", flags=0, contig_idx=0, start=100 + i, mapq=60,
             cigar="10M", seq="ACGTACGTAC", qual="I" * 10,
             read_group_idx=-1)
        for i in range(8)
    ]
    batch, side = pack_reads(recs)
    return batch, side, SamHeader()


def test_part_writer_pool_atomic_success_leaves_no_staging(tmp_path):
    from adam_tpu.io.parquet import TMP_DIR_NAME, PartWriterPool

    batch, side, header = _tiny_dataset()
    pool = PartWriterPool(n_encoders=1, inflight_parts=2)
    for i in range(3):
        pool.submit(str(tmp_path / f"part-r-{i:05d}.parquet"), batch,
                    side, header)
    pool.close()
    names = sorted(os.listdir(tmp_path))
    assert names == [f"part-r-{i:05d}.parquet" for i in range(3)]
    assert not (tmp_path / TMP_DIR_NAME).exists()


def test_part_writer_pool_write_error_original_traceback(tmp_path):
    """close() re-raises the FIRST worker exception itself (traceback
    intact), submit() fails fast afterwards, and no unpublished staging
    files survive — and nothing deadlocks on the submit gate."""
    from adam_tpu.io.parquet import TMP_DIR_NAME, PartWriterPool

    batch, side, header = _tiny_dataset()
    faults.install("parquet.write=transient")
    pool = PartWriterPool(n_encoders=1, inflight_parts=2)
    pool.submit(str(tmp_path / "part-r-00000.parquet"), batch, side,
                header)
    # wait for the worker failure, then submit must fail fast (chained
    # to the original) instead of queueing behind a dead writer
    deadline = time.time() + 10
    while pool.failed is None and time.time() < deadline:
        time.sleep(0.01)
    assert pool.failed is not None
    with pytest.raises(RuntimeError) as ei:
        pool.submit(str(tmp_path / "part-r-00001.parquet"), batch, side,
                    header)
    assert isinstance(ei.value.__cause__, faults.TransientFault)
    with pytest.raises(faults.TransientFault) as ei2:
        pool.close()
    # the original exception object: its traceback walks the worker
    assert ei2.value.__traceback__ is not None
    assert not (tmp_path / TMP_DIR_NAME).exists()
    assert not list(tmp_path.glob("*.parquet"))


def test_save_alignments_atomic_publish(tmp_path):
    from adam_tpu.io import parquet as pq_io

    batch, side, header = _tiny_dataset()
    out = tmp_path / "single.adam"
    pq_io.save_alignments(str(out), batch, side, header)
    assert out.exists()
    assert not (tmp_path / pq_io.TMP_DIR_NAME).exists()


def test_checkpoint_manifest_atomic_and_tolerant(tmp_path):
    from adam_tpu.pipelines.checkpoint import StageCheckpointer

    d = str(tmp_path / "ck")
    ck = StageCheckpointer(d, ["a", "b"])
    ck.mark("a")
    # atomic write: the temp name never survives a successful mark
    assert not os.path.exists(os.path.join(d, "MANIFEST.json.tmp"))
    with open(os.path.join(d, "MANIFEST.json")) as fh:
        assert json.load(fh)["completed"] == ["a"]
    # corrupt manifest: resume treats it as no checkpoint, not a crash
    with open(os.path.join(d, "MANIFEST.json"), "w") as fh:
        fh.write('{"stages": ["a", "b", TRUNC')
    ck2 = StageCheckpointer(d, ["a", "b"])
    assert ck2.last_completed() is None
    ck2.mark("a")  # and the next mark heals it atomically
    with open(os.path.join(d, "MANIFEST.json")) as fh:
        assert json.load(fh)["completed"] == ["a"]


def test_checkpoint_mark_idempotent_and_fingerprint_invalidates(tmp_path):
    """`mark()` never grows duplicate completed entries, and a manifest
    recorded under a different input/flag fingerprint is ignored (a
    recompute) instead of silently reloading stale stage stores."""
    from adam_tpu.pipelines.checkpoint import StageCheckpointer

    d = str(tmp_path / "ck")
    ck = StageCheckpointer(d, ["a", "b"], fingerprint="fp1")
    ck.mark("a")
    ck.mark("a")  # rerun double-mark: no duplicate
    with open(os.path.join(d, "MANIFEST.json")) as fh:
        m = json.load(fh)
    assert m["completed"] == ["a"] and m["fingerprint"] == "fp1"
    # the stage store must exist for resume (last_completed filters)
    open(os.path.join(d, "a.adam"), "w").write("x")
    assert StageCheckpointer(d, ["a", "b"],
                             fingerprint="fp1").last_completed() == "a"
    # changed input/flags -> different fingerprint -> no resume
    ck2 = StageCheckpointer(d, ["a", "b"], fingerprint="fp2")
    assert ck2.last_completed() is None
    # a legacy manifest without a fingerprint is equally untrusted
    with open(os.path.join(d, "MANIFEST.json"), "w") as fh:
        json.dump({"stages": ["a", "b"], "completed": ["a"]}, fh)
    assert StageCheckpointer(d, ["a", "b"],
                             fingerprint="fp1").last_completed() is None
    # ... but a fingerprint-less caller (the legacy API) still resumes
    assert StageCheckpointer(d, ["a", "b"]).last_completed() == "a"


def test_compose_and_input_fingerprints(tmp_path):
    from adam_tpu.pipelines import checkpoint as ck

    p = str(tmp_path / "in.sam")
    open(p, "w").write("@HD\tVN:1.5\nr1\t0\tc\t1\t60\t4M\t*\t0\t0\tACGT\tIIII\n")
    f1 = ck.input_fingerprint(p)
    assert f1 == ck.input_fingerprint(p)  # stable
    # content identity, not path identity
    p2 = str(tmp_path / "moved.sam")
    os.rename(p, p2)
    assert ck.input_fingerprint(p2) == f1
    open(p2, "a").write("r2\t4\t*\t0\t0\t*\t*\t0\t0\tACGT\tIIII\n")
    assert ck.input_fingerprint(p2) != f1
    # flag composition: value changes and array-content changes flip it
    base = {"input": f1, "window_reads": 256,
            "known": np.arange(4, dtype=np.int64)}
    fp = ck.compose_fingerprint(base)
    assert fp == ck.compose_fingerprint(dict(base))
    assert fp != ck.compose_fingerprint({**base, "window_reads": 512})
    assert fp != ck.compose_fingerprint(
        {**base, "known": np.arange(1, 5, dtype=np.int64)}
    )


# ---------------------------------------------------------------------------
# Streamed matrix on the virtual mesh (bit-compared to a fault-free run)
# ---------------------------------------------------------------------------
def _parts_hash(out_dir: str) -> dict:
    return {
        f: hashlib.sha256(
            open(os.path.join(out_dir, f), "rb").read()
        ).hexdigest()
        for f in os.listdir(out_dir) if f.startswith("part-")
    }


@pytest.fixture(scope="module")
def wgs_input(tmp_path_factory):
    from make_wgs_sam import make_wgs

    d = tmp_path_factory.mktemp("faults")
    path = str(d / "in.sam")
    make_wgs(path, 2048, 100, n_contigs=2, contig_len=30_000,
             indel_every=800, snp_every=400)
    return d, path


@pytest.fixture(scope="module")
def clean_baseline(wgs_input):
    """Fault-free single-chip reference run (device backend)."""
    from adam_tpu.pipelines.streamed import transform_streamed

    d, path = wgs_input
    out = str(d / "clean1.adam")
    os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
    try:
        transform_streamed(path, out, window_reads=256, devices=1)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    return _parts_hash(out)


def _faulted_run(path, out, spec, devices, env=None):
    from adam_tpu.pipelines.streamed import transform_streamed

    os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
    os.environ.update(env or {})
    was = tele.TRACE.recording
    tele.TRACE.recording = True
    tele.TRACE.reset()
    faults.install(spec)
    try:
        stats = transform_streamed(path, out, window_reads=256,
                                   devices=devices)
    finally:
        faults.clear()
        snap = tele.TRACE.snapshot()
        tele.TRACE.recording = was
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
        for k in env or {}:
            os.environ.pop(k, None)
    return stats, snap


def test_streamed_acceptance_transient_plus_permanent(
    wgs_input, clean_baseline
):
    """The ISSUE acceptance scenario: every 3rd dispatch faults
    transiently and device 1 dies permanently, on the full 8-device
    mesh — the run completes, output is bit-identical to the fault-free
    single-chip run, device.evicted == 1 and retry.attempts > 0."""
    d, path = wgs_input
    out = str(d / "acc8.adam")
    stats, snap = _faulted_run(
        path, out,
        "device.dispatch=transient,every=3;"
        "device.dispatch=permanent,device=1,times=1",
        devices=8,
    )
    assert stats["n_devices"] == 8
    assert snap["counters"][tele.C_DEVICE_EVICTED] == 1
    assert snap["counters"][tele.C_RETRY_ATTEMPTS] > 0
    assert snap["counters"][tele.C_FAULT_INJECTED] > 0
    assert _parts_hash(out) == clean_baseline


def test_streamed_fetch_failure_evicts_and_replays(
    wgs_input, clean_baseline
):
    """Persistent fetch failures from one chip spend the retry budget,
    evict it, and replay its windows on survivors under the
    device.pool.replay span."""
    d, path = wgs_input
    out = str(d / "fetch4.adam")
    _stats, snap = _faulted_run(
        path, out, "device.fetch=transient,device=1", devices=4,
    )
    assert snap["counters"][tele.C_DEVICE_EVICTED] == 1
    assert snap["spans"][tele.SPAN_POOL_REPLAY]["count"] >= 1
    assert _parts_hash(out) == clean_baseline


def test_streamed_last_device_loss_falls_back_to_host(
    wgs_input, clean_baseline
):
    """Permanent faults kill both pool devices; the run degrades to the
    native/numpy host backend and still matches bit-for-bit."""
    d, path = wgs_input
    out = str(d / "lost2.adam")
    _stats, snap = _faulted_run(
        path, out, "device.dispatch=permanent", devices=2,
    )
    assert snap["counters"][tele.C_DEVICE_EVICTED] == 2
    assert _parts_hash(out) == clean_baseline


def test_streamed_mid_stream_device_loss_keeps_window_order(
    wgs_input, clean_baseline
):
    """The device path dies while older windows are still in flight in
    pass A's pending queue (after=4 skips the first windows' dispatches)
    — the pending windows must drain BEFORE the failing window's host
    summary, or the resolve barrier's window-offset slices apply
    duplicate flags to the wrong rows."""
    d, path = wgs_input
    out = str(d / "midloss2.adam")
    _stats, snap = _faulted_run(
        path, out, "device.dispatch=permanent,after=4", devices=2,
    )
    assert snap["counters"][tele.C_DEVICE_EVICTED] == 2
    assert _parts_hash(out) == clean_baseline


def test_streamed_hung_fetch_times_out_and_retries(
    wgs_input, clean_baseline
):
    """A hung fetch RPC (injected 5 s stall) trips the deadline
    watchdog, surfaces as a retryable timeout, and the retried fetch
    completes the run unchanged."""
    d, path = wgs_input
    out = str(d / "hang2.adam")
    _stats, snap = _faulted_run(
        path, out, "device.fetch=delay:5,times=1", devices=2,
        env={"ADAM_TPU_FETCH_TIMEOUT_S": "0.3"},
    )
    assert snap["counters"][tele.C_RETRY_ATTEMPTS] >= 1
    assert snap["counters"].get(tele.C_DEVICE_EVICTED, 0) == 0
    assert _parts_hash(out) == clean_baseline


def test_streamed_killed_mid_write_leaves_no_partial_parts(
    wgs_input, clean_baseline
):
    """SIGKILL while a part write is in flight: the output directory
    holds no *.tmp and no truncated part (unpublished writes live in
    the ignored _temporary staging dir), and a rerun starts clean and
    produces the bit-identical full output."""
    import pyarrow.parquet as pq

    d, path = wgs_input
    out = str(d / "killed.adam")
    driver = (
        "import sys\n"
        "try:\n"
        "    import jax, jax._src.xla_bridge as xb\n"
        "    xb._backend_factories.pop('axon', None)\n"
        "    jax.config.update('jax_platforms', 'cpu')\n"
        "except Exception: pass\n"
        "from adam_tpu.pipelines.streamed import transform_streamed\n"
        "transform_streamed(sys.argv[1], sys.argv[2], window_reads=256)\n"
    )
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # host backend: the crash path under test is the writer, and a
        # subprocess chip probe would only slow the kill window down
        "ADAM_TPU_BQSR_BACKEND": "numpy",
        # part 0 publishes, then part 1's write stalls 30 s: a
        # deterministic kill window with one part published and one
        # unpublished in flight
        "ADAM_TPU_FAULTS": "parquet.write=delay:30,after=1,times=1",
    })
    proc = subprocess.Popen(
        [sys.executable, "-c", driver, path, out],
        env=env, cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    staging = os.path.join(out, "_temporary")
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.isdir(out) and any(
                f.startswith("part-") for f in os.listdir(out)
            ):
                break
            if proc.poll() is not None:
                pytest.fail("driver exited before publishing a part")
            time.sleep(0.05)
        time.sleep(0.3)  # let the stalled write reach mid-flight
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    # crash consistency: no torn/temp parts visible to readers
    top = os.listdir(out)
    assert not [f for f in top if f.endswith(".tmp")], top
    for f in top:
        if f.startswith("part-"):
            pq.read_table(os.path.join(out, f))  # parses = not truncated
    # rerun over the same output dir: stale staging purged, full output
    from adam_tpu.pipelines.streamed import transform_streamed

    os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
    try:
        transform_streamed(path, out, window_reads=256, devices=1)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    assert not os.path.isdir(staging)
    assert _parts_hash(out) == clean_baseline
