"""Anomaly-triggered incident bundles (utils/incidents.py,
docs/OBSERVABILITY.md "Incident bundles").

The contract under test: armed on a run dir, a trigger snapshots the
flight-recorder tail + metrics + health board + the triggering trace
into ONE self-contained, atomically-written JSON bundle; recording is
cooldown-limited per trigger, bounded in count, best-effort (never
raises into the run), and disarmed costs one predicate.  Bundles list
via `adam-tpu incidents`, fold into `adam-tpu analyze` reports, and
feed the heartbeat's last_incident fields.
"""

import json
import os

import pytest

from adam_tpu.utils import incidents
from adam_tpu.utils import telemetry as tele

TID = "ab" * 8


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh recorder state per test; cooldown off unless a test opts
    back in."""
    incidents._reset_for_tests()
    monkeypatch.setenv("ADAM_TPU_INCIDENT_COOLDOWN_S", "0")
    yield
    incidents._reset_for_tests()


def _traced_tracer():
    """A recording tracer carrying the spans an audit bundle must
    embed: dispatch, fetch, audit-check on the implicated window."""
    tr = tele.Tracer(recording=True)
    tr.set_trace(TID)
    with tr.span(tele.SPAN_APPLY_DISPATCH, window=12, device=0):
        pass
    with tr.span(tele.SPAN_APPLY_FETCH, window=12, device=0):
        pass
    with tr.span(tele.SPAN_AUDIT_CHECK, window=12, device=0):
        pass
    return tr


def test_disarmed_records_nothing(tmp_path):
    assert not incidents.installed()
    assert incidents.maybe_record("hedge.fired", reason="x") is None
    assert incidents.last_incident() is None
    assert list((tmp_path).iterdir()) == []


def test_bundle_contents_and_listing(tmp_path):
    incidents.install(str(tmp_path))
    tr = _traced_tracer()
    path = incidents.maybe_record(
        "audit.mismatch", device="0", window=12, tracer=tr,
        reason="SDC dual-compute mismatch on window 12",
    )
    assert path and os.path.isfile(path)
    doc = json.load(open(path))
    assert doc["schema"] == incidents.INCIDENT_SCHEMA
    assert doc["trigger"] == "audit.mismatch"
    assert doc["device"] == "0" and doc["window"] == 12
    assert doc["trace_id"] == TID  # defaulted from the tracer
    assert doc["events"] and doc["events_dropped"] == 0
    assert doc["metrics"]["events_recorded"] >= 3
    # the embedded trace is the /trace-shaped view of the implicated
    # window: dispatch + fetch + audit spans present (the chaos-run
    # acceptance criterion reads exactly these)
    names = {e["name"] for e in doc["trace"]["traceEvents"]
             if e.get("ph") == "X"}
    assert {tele.SPAN_APPLY_DISPATCH, tele.SPAN_APPLY_FETCH,
            tele.SPAN_AUDIT_CHECK} <= names
    # listing: run dir and the incidents dir itself both resolve
    for probe in (str(tmp_path), os.path.join(str(tmp_path),
                                              "incidents")):
        rows = incidents.list_bundles(probe)
        assert [r["trigger"] for r in rows] == ["audit.mismatch"]
        assert rows[0]["trace_id"] == TID and rows[0]["window"] == 12
    last = incidents.last_incident()
    assert last["id"] == doc["id"] and last["trigger"] == "audit.mismatch"


def test_cooldown_limits_per_trigger(tmp_path, monkeypatch):
    monkeypatch.setenv("ADAM_TPU_INCIDENT_COOLDOWN_S", "3600")
    incidents.install(str(tmp_path))
    assert incidents.maybe_record("hedge.fired", reason="a")
    assert incidents.maybe_record("hedge.fired", reason="b") is None
    # a DIFFERENT trigger has its own cooldown clock
    assert incidents.maybe_record("health.transition", reason="c")
    assert len(incidents.list_bundles(str(tmp_path))) == 2


def test_master_toggle_disables(tmp_path, monkeypatch):
    incidents.install(str(tmp_path))
    monkeypatch.setenv("ADAM_TPU_INCIDENTS", "0")
    assert incidents.maybe_record("hedge.fired", reason="x") is None
    monkeypatch.setenv("ADAM_TPU_INCIDENTS", "1")
    assert incidents.maybe_record("hedge.fired", reason="x")


def test_bundle_count_is_bounded(tmp_path, monkeypatch):
    monkeypatch.setenv("ADAM_TPU_INCIDENT_MAX", "3")
    incidents.install(str(tmp_path))
    paths = [incidents.maybe_record("hedge.fired", reason=str(i))
             for i in range(6)]
    assert all(paths)
    rows = incidents.list_bundles(str(tmp_path))
    assert len(rows) == 3
    # oldest pruned first: the survivors are the NEWEST three
    assert [r["path"] for r in rows] == sorted(paths)[-3:]


def test_event_cap_keeps_newest_tail(tmp_path, monkeypatch):
    monkeypatch.setenv("ADAM_TPU_INCIDENT_EVENTS", "4")
    incidents.install(str(tmp_path))
    tr = tele.Tracer(recording=True)
    for i in range(10):
        with tr.span(tele.SPAN_TOKENIZE, window=i):
            pass
    doc = json.load(open(incidents.maybe_record(
        "hedge.fired", tracer=tr, reason="x")))
    assert len(doc["events"]) == 4
    assert doc["events_dropped"] == 6
    assert [e["args"]["window"] for e in doc["events"]] == [6, 7, 8, 9]


def test_quota_burst_detector(tmp_path, monkeypatch):
    monkeypatch.setenv("ADAM_TPU_INCIDENT_QUOTA_BURST", "3")
    monkeypatch.setenv("ADAM_TPU_INCIDENT_QUOTA_WINDOW_S", "60")
    incidents.install(str(tmp_path))
    incidents.note_quota_rejected("acme")
    incidents.note_quota_rejected("acme")
    assert incidents.list_bundles(str(tmp_path)) == []
    incidents.note_quota_rejected("globex")
    rows = incidents.list_bundles(str(tmp_path))
    assert [r["trigger"] for r in rows] == ["quota.burst"]
    assert "acme" in rows[0]["reason"] and "globex" in rows[0]["reason"]
    # the window drained on fire: the next rejection starts fresh
    incidents.note_quota_rejected("acme")
    assert len(incidents.list_bundles(str(tmp_path))) == 1


def test_recording_is_best_effort(tmp_path, monkeypatch):
    """A broken bundle write is logged and swallowed — never raised
    into the triggering run."""
    incidents.install(str(tmp_path))

    def boom(*a, **kw):
        raise OSError("disk on fire")

    import adam_tpu.utils.durability as dur

    monkeypatch.setattr(dur, "atomic_write_json", boom)
    assert incidents.maybe_record("hedge.fired", reason="x") is None


def test_listing_skips_malformed_and_foreign(tmp_path):
    incidents.install(str(tmp_path))
    incidents.maybe_record("hedge.fired", reason="good")
    d = incidents.incidents_dir()
    with open(os.path.join(d, "inc-0-0000-torn.json"), "w") as fh:
        fh.write("{not json")
    with open(os.path.join(d, "inc-0-0001-alien.json"), "w") as fh:
        json.dump({"schema": "other/9"}, fh)
    rows = incidents.list_bundles(str(tmp_path))
    assert [r["trigger"] for r in rows] == ["hedge.fired"]


def test_retry_exhausted_trigger_fires(tmp_path):
    """A genuinely spent retry budget records a retry.exhausted bundle
    (utils/retry.retry_call's hook); a permanent failure on attempt 1
    never consumed the budget, so it records nothing."""
    from adam_tpu.utils.retry import (PermanentFault, RetryPolicy,
                                      TransientFault, retry_call)

    incidents.install(str(tmp_path))
    policy = RetryPolicy(attempts=2, backoff_s=0.0)

    def permanent():
        raise PermanentFault("not an incident")

    with pytest.raises(PermanentFault):
        retry_call(permanent, site="test.perm", policy=policy)
    assert incidents.list_bundles(str(tmp_path)) == []

    def always_transient():
        raise TransientFault("injected")

    with pytest.raises(TransientFault):
        retry_call(always_transient, site="test.spent", policy=policy)
    rows = incidents.list_bundles(str(tmp_path))
    assert [r["trigger"] for r in rows] == ["retry.exhausted"]
    assert "test.spent" in rows[0]["reason"]


def test_health_transition_trigger_fires(tmp_path):
    """A health-board demotion staged under the board lock fires its
    bundle AFTER release — and the bundle embeds the board snapshot
    (the deadlock this ordering exists to avoid)."""
    from adam_tpu.utils import health

    incidents.install(str(tmp_path))
    board = health.BOARD  # the global: the bundle snapshots it too
    tr = tele.Tracer(recording=True)
    try:
        for _ in range(8):  # enough retry weight to cross suspect
            board.note_retry(0, site="test", tracer=tr)
        rows = incidents.list_bundles(str(tmp_path))
        assert [r["trigger"] for r in rows] == ["health.transition"]
        doc = json.load(open(rows[0]["path"]))
        assert "suspect" in doc["reason"]
        assert doc["health"], "bundle missing the board snapshot"
    finally:
        board.reset()


def test_cli_incidents_table_and_json(tmp_path, capsys):
    from adam_tpu.cli.main import main

    incidents.install(str(tmp_path))
    tr = _traced_tracer()
    incidents.maybe_record("audit.mismatch", device="0", window=12,
                           tracer=tr, reason="bitflip")
    assert main(["incidents", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "TRIGGER" in out and "audit.mismatch" in out
    assert TID in out and "bitflip" in out
    assert main(["incidents", str(tmp_path), "-json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == incidents.INCIDENT_SCHEMA + "+list"
    assert doc["incidents"][0]["window"] == 12
    # empty dir: clean exit, explicit "none"
    assert main(["incidents", str(tmp_path / "empty")]) == 0
    assert "none" in capsys.readouterr().out


def test_analyzer_folds_sibling_incidents(tmp_path):
    """`adam-tpu analyze` on an artifact next to an incidents/ dir
    renders the Incidents section (trigger, device, window, trace)."""
    from adam_tpu.utils import analyzer

    incidents.install(str(tmp_path))
    tr = _traced_tracer()
    incidents.maybe_record("audit.mismatch", device="0", window=12,
                           tracer=tr, reason="bitflip caught")
    art = tmp_path / "m.json"
    art.write_text(json.dumps(tr.snapshot()))
    report = analyzer.analyze_path(str(art))
    assert report["incidents"], "incidents not folded into the report"
    text = analyzer.render_report(report)
    assert "Incidents (1 bundle(s))" in text
    assert "audit.mismatch" in text and "window 12" in text
    assert TID in text and "bitflip caught" in text
