"""BQSR differential tests against the GATK-derived golden observation
table (the reference's BaseQualityRecalibrationSuite methodology:
sorted-CSV-line comparison against bqsr1-ref.observed)."""

import numpy as np
import pytest

from adam_tpu.formats import schema
from adam_tpu.io import load_alignments
from adam_tpu.models.snp_table import SnpTable
from adam_tpu.pipelines.bqsr import (
    build_observation_table,
    compute_cycles,
    compute_dinucs,
    recalibrate_base_qualities,
)


def test_cycle_covariate():
    import jax.numpy as jnp

    lengths = jnp.array([4, 4, 4, 4])
    P, S, R = 0x1, 0x80, 0x10
    flags = jnp.array([P | 0x40, P | S, R | P | 0x40, R | P | S])
    cyc = np.asarray(compute_cycles(lengths, flags, 4))
    np.testing.assert_array_equal(cyc[0], [1, 2, 3, 4])       # fwd first
    np.testing.assert_array_equal(cyc[1], [-1, -2, -3, -4])   # fwd second
    np.testing.assert_array_equal(cyc[2], [4, 3, 2, 1])       # rev first
    np.testing.assert_array_equal(cyc[3], [-4, -3, -2, -1])   # rev second
    # unpaired behaves as first-of-pair
    cyc2 = np.asarray(compute_cycles(jnp.array([4]), jnp.array([0]), 4))
    np.testing.assert_array_equal(cyc2[0], [1, 2, 3, 4])


def test_dinuc_covariate():
    import jax.numpy as jnp

    # forward ACGT: (-, A), (A,C), (C,G), (G,T)
    bases = jnp.asarray(schema.encode_bases("ACGT")[None, :])
    d = np.asarray(compute_dinucs(bases, jnp.array([4]), jnp.array([0]), 4))
    A, C, G, T = 0, 1, 2, 3
    np.testing.assert_array_equal(d[0], [16, A * 4 + C, C * 4 + G, G * 4 + T])
    # reverse: machine read = revcomp(ACGT) = ACGT; dinuc[i] = (comp(s[i+1]), comp(s[i]))
    d = np.asarray(compute_dinucs(bases, jnp.array([4]), jnp.array([0x10]), 4))
    np.testing.assert_array_equal(d[0], [G * 4 + T, C * 4 + G, A * 4 + C, 16])
    # N breaks pairs
    basesn = jnp.asarray(schema.encode_bases("ANGT")[None, :])
    d = np.asarray(compute_dinucs(basesn, jnp.array([4]), jnp.array([0]), 4))
    np.testing.assert_array_equal(d[0], [16, 16, 16, G * 4 + T])


@pytest.mark.slow
def test_bqsr_observation_table_matches_golden(ref_resources):
    """Exact parity with GATK-derived bqsr1-ref.observed, the reference's
    own golden-file test (BaseQualityRecalibrationSuite.scala:30-47)."""
    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    snps = SnpTable.from_file(str(ref_resources / "bqsr1.snps"))
    obs = build_observation_table(ds, snps)
    ours = sorted(l for l in obs.to_csv().split("\n") if l)
    golden = sorted(
        l for l in (ref_resources / "bqsr1-ref.observed").read_text().splitlines() if l
    )
    assert len(ours) == len(golden)
    for a, b in zip(ours, golden):
        assert a == b


@pytest.mark.slow
def test_bqsr_recalibrates_quals(ref_resources):
    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    snps = SnpTable.from_file(str(ref_resources / "bqsr1.snps"))
    out = recalibrate_base_qualities(ds, snps)
    b0, b1 = ds.batch.to_numpy(), out.batch.to_numpy()
    assert b1.n_rows == b0.n_rows
    valid = np.asarray(b0.valid)
    # quality distribution must change but low quals (<Q5) are untouched
    changed = (np.asarray(b0.quals) != np.asarray(b1.quals)) & valid[:, None]
    assert changed.any()
    low = (np.asarray(b0.quals) < 5) & (np.asarray(b0.quals) > 0) & valid[:, None]
    assert (np.asarray(b1.quals)[low] == np.asarray(b0.quals)[low]).all()
    # capped at Q50 wherever recalibration applied
    in_read = np.arange(b0.lmax)[None, :] < np.asarray(b0.lengths)[:, None]
    assert (np.asarray(b1.quals)[changed & in_read] <= 50).all()
    # original quals stashed
    assert any(q is not None for q in out.sidecar.orig_quals)


def test_snp_table(ref_resources):
    snps = SnpTable.from_file(str(ref_resources / "bqsr1.snps"))
    assert len(snps) > 0
    assert snps.contains("22", 16050612 - 1)
    assert not snps.contains("22", 12345)
    mask = snps.mask_positions(
        ["21", "22"],
        np.array([1, 0]),
        np.array([[16050611, 16050610], [16050611, -1]]),
    )
    np.testing.assert_array_equal(mask, [[True, False], [False, False]])


def test_phred_table_host_device_parity(ref_resources):
    """The host (numpy) recalibration table must match the device kernel
    bit-for-bit on real observation data."""
    import jax.numpy as jnp

    from adam_tpu.io.context import load_alignments
    from adam_tpu.pipelines import bqsr as B

    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    obs = build_observation_table(ds)
    host = B.recalibration_phred_table_np(obs.total, obs.mismatches)
    dev = np.asarray(
        B.recalibration_phred_table(
            jnp.asarray(obs.total), jnp.asarray(obs.mismatches)
        )
    )
    np.testing.assert_array_equal(host, dev)


def test_known_sites_native_masking_matches_python(ref_resources):
    """The native kernel's in-walk SNP masking (sorted site-key binary
    search) produces the same observation table as the explicit python
    [N, L] mask path."""
    from adam_tpu import native
    from adam_tpu.pipelines import bqsr as bqsr_mod

    if not native.available():
        pytest.skip("native codec unavailable")
    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    b = ds.batch.to_numpy()
    # mask a handful of real covered positions on every contig
    table = {}
    for ci, name in enumerate(ds.seq_dict.names):
        rows = np.flatnonzero((np.asarray(b.contig_idx) == ci) & b.valid)
        if len(rows):
            starts = np.asarray(b.start)[rows[:50]]
            table[name] = np.concatenate(
                [starts + k for k in range(20)]
            )
    snps = SnpTable(table)
    native_tab = build_observation_table(ds, known_snps=snps)

    # python-mask path: disable native for the observation pass
    orig = native.bqsr_observe
    native.bqsr_observe = lambda *a, **k: None
    try:
        py_tab = build_observation_table(ds, known_snps=snps)
    finally:
        native.bqsr_observe = orig
    assert sorted(native_tab.to_csv().splitlines()) == sorted(
        py_tab.to_csv().splitlines()
    )
    # and masking actually removed observations vs the unmasked table
    unmasked = build_observation_table(ds)
    assert native_tab.total.sum() < unmasked.total.sum()


def _observe_read_ok(b, has_md):
    """The _observe_device canonical-read mask (bqsr.py), test-side
    single copy for the differential tests."""
    flags = np.asarray(b.flags)
    return (
        np.asarray(b.valid)
        & ((flags & schema.FLAG_UNMAPPED) == 0)
        & ((flags & (schema.FLAG_SECONDARY | schema.FLAG_SUPPLEMENTARY)) == 0)
        & ((flags & schema.FLAG_DUPLICATE) == 0)
        & ((flags & schema.FLAG_FAILED_QC) == 0)
        & np.asarray(b.has_qual)
        & (np.asarray(b.mapq) > 0)
        & (np.asarray(b.mapq) != 255)
        & has_md
    )


def test_inline_md_observe_matches_tokenized_mask(ref_resources):
    """The native walk's inline MD parse must produce the same histograms
    as feeding it the host-tokenized [N, L] mismatch mask."""
    from adam_tpu import native
    from adam_tpu.formats.batch import grid_cols
    from adam_tpu.ops.mdtag import batch_md_arrays
    from adam_tpu.pipelines import bqsr as bq

    if not native.available():
        pytest.skip("native library unavailable")
    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    t1, m1, _, gl = bq._observe_device(ds, None)
    b = ds.batch.to_numpy()
    is_mm, _, has_md = batch_md_arrays(ds.batch, ds.sidecar,
                                       need_ref_codes=False)
    read_ok = _observe_read_ok(b, has_md)
    t2, m2 = native.bqsr_observe(
        b.bases, b.quals, b.lengths, b.flags, b.read_group_idx,
        b.cigar_ops, b.cigar_lens, b.cigar_n, None, is_mm, read_ok,
        len(ds.read_groups) + 1, grid_cols(b.lmax),
        contig_idx=b.contig_idx, start=b.start,
    )
    np.testing.assert_array_equal(np.asarray(t1), t2)
    np.testing.assert_array_equal(np.asarray(m1), m2)


def test_inline_md_observe_matches_tokenized_mask_wgs(tmp_path):
    """Same differential on WGS-shaped data (indels, soft clips, dense
    SNP/indel planting) with known-site masking active."""
    import os
    import sys

    from adam_tpu import native
    from adam_tpu.api.datasets import GenotypeDataset
    from adam_tpu.formats.batch import grid_cols
    from adam_tpu.ops.mdtag import batch_md_arrays
    from adam_tpu.pipelines import bqsr as bq

    if not native.available():
        pytest.skip("native library unavailable")
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
    )
    from make_wgs_sam import make_wgs

    sam = str(tmp_path / "w.sam")
    vcf = str(tmp_path / "w.vcf")
    make_wgs(sam, 4096, 100, n_contigs=2, contig_len=40_000,
             indel_every=800, snp_every=400, known_sites_out=vcf)
    ds = load_alignments(sam)
    known = GenotypeDataset.load(
        vcf, contig_names=ds.seq_dict.names
    ).snp_table()
    t1, m1, _, gl = bq._observe_device(ds, known)
    b = ds.batch.to_numpy()
    is_mm, _, has_md = batch_md_arrays(ds.batch, ds.sidecar,
                                       need_ref_codes=False)
    read_ok = _observe_read_ok(b, has_md)
    t2, m2 = native.bqsr_observe(
        b.bases, b.quals, b.lengths, b.flags, b.read_group_idx,
        b.cigar_ops, b.cigar_lens, b.cigar_n, None, is_mm, read_ok,
        len(ds.read_groups) + 1, gl,
        contig_idx=b.contig_idx, start=b.start,
        snp_keys=known.site_keys(ds.seq_dict.names),
    )
    assert int(t2.sum()) > 0 and int(m2.sum()) > 0
    np.testing.assert_array_equal(np.asarray(t1), t2)
    np.testing.assert_array_equal(np.asarray(m1), m2)
