import numpy as np
import pytest
import jax.numpy as jnp

from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.ops import cigar as cigar_ops
from adam_tpu.ops import flagstat as fs
from adam_tpu.ops import kmer as kmer_ops
from adam_tpu.ops import phred
from adam_tpu.ops import smith_waterman as sw
from adam_tpu.ops.mdtag import MdTag, batch_md_arrays


# ------------------------------------------------------------------- phred
def test_phred_tables():
    np.testing.assert_allclose(
        np.asarray(phred.phred_to_error_probability(np.array([0, 10, 20]))),
        [1.0, 0.1, 0.01],
    )
    assert int(phred.error_probability_to_phred(0.001)) == 30
    assert int(phred.success_probability_to_phred(0.999)) == 30
    # reference rounding rule: math.round(-10*log10(p))
    assert int(phred.error_probability_to_phred(0.0005)) == 33


# ------------------------------------------------------------------- cigar
def _cig_batch(cigs, starts):
    recs = [
        dict(name=f"r{i}", flags=0, contig_idx=0, start=s, mapq=60, cigar=c,
             seq="A" * schema.cigar_str_stats(c)[0], qual=None)
        for i, (c, s) in enumerate(zip(cigs, starts))
    ]
    b, _ = pack_reads(recs)
    return b.to_device()


def test_cigar_walks():
    b = _cig_batch(["10M", "2S8M", "3M2I3M2D2M", "2H4M3S"], [100, 100, 100, 100])
    rl = np.asarray(cigar_ops.reference_length(b.cigar_ops, b.cigar_lens, b.cigar_n))
    np.testing.assert_array_equal(rl, [10, 8, 10, 4])
    ql = np.asarray(cigar_ops.query_length(b.cigar_ops, b.cigar_lens, b.cigar_n))
    np.testing.assert_array_equal(ql, [10, 10, 10, 7])
    lead = np.asarray(cigar_ops.leading_clip(b.cigar_ops, b.cigar_lens, b.cigar_n))
    np.testing.assert_array_equal(lead, [0, 2, 0, 2])
    trail = np.asarray(cigar_ops.trailing_clip(b.cigar_ops, b.cigar_lens, b.cigar_n))
    np.testing.assert_array_equal(trail, [0, 0, 0, 3])
    us = np.asarray(cigar_ops.unclipped_start(b.start, b.cigar_ops, b.cigar_lens, b.cigar_n))
    np.testing.assert_array_equal(us, [100, 98, 100, 98])


def test_five_prime_position():
    # forward read: unclipped start; reverse: exclusive unclipped end
    b = _cig_batch(["2S8M", "2S8M"], [100, 100])
    flags = np.array([0, schema.FLAG_REVERSE], np.int32)
    fp = np.asarray(
        cigar_ops.five_prime_position(
            b.start, b.end, flags, b.cigar_ops, b.cigar_lens, b.cigar_n
        )
    )
    np.testing.assert_array_equal(fp, [98, 108])


def test_reference_positions():
    b = _cig_batch(["2S3M2D3M", "3M2I1M"], [10, 50])
    rp = np.asarray(
        cigar_ops.reference_positions(
            b.cigar_ops, b.cigar_lens, b.cigar_n, b.start, b.lmax
        )
    )
    np.testing.assert_array_equal(rp[0, :8], [-1, -1, 10, 11, 12, 15, 16, 17])
    np.testing.assert_array_equal(rp[1, :6], [50, 51, 52, -1, -1, 53])


# ---------------------------------------------------------------- flagstat
def test_flagstat_small(ref_resources):
    from adam_tpu.io import load_alignments

    ds = load_alignments(str(ref_resources / "small.sam"))
    failed, passed = ds.flagstat()
    assert passed.total == 20
    assert failed.total == 0
    assert passed.mapped == 20
    assert passed.paired_in_sequencing == 0
    out = fs.format_flagstat(failed, passed)
    assert "20 + 0 in total" in out
    assert "20 + 0 mapped (100.00%:0.00%)" in out


def test_flagstat_paired_flags():
    P, M, U = schema.FLAG_PAIRED, schema.FLAG_MATE_UNMAPPED, schema.FLAG_UNMAPPED
    recs = [
        dict(name="a", flags=P | 0x40 | 0x2, contig_idx=0, start=10, mapq=60,
             cigar="4M", seq="ACGT", qual="IIII", mate_contig_idx=1, mate_start=50),
        dict(name="b", flags=P | 0x80, contig_idx=1, start=50, mapq=3,
             cigar="4M", seq="ACGT", qual="IIII", mate_contig_idx=0, mate_start=10),
        dict(name="c", flags=P | M, contig_idx=0, start=20, mapq=60,
             cigar="4M", seq="ACGT", qual="IIII"),
        dict(name="d", flags=U | 0x200, contig_idx=-1, start=-1, mapq=0,
             cigar="*", seq="ACGT", qual="IIII"),
        dict(name="e", flags=schema.FLAG_DUPLICATE, contig_idx=0, start=30,
             mapq=60, cigar="4M", seq="ACGT", qual="IIII", mate_contig_idx=-1),
    ]
    b, _ = pack_reads(recs)
    failed, passed = fs.flagstat(b)
    assert passed.total == 4 and failed.total == 1
    assert passed.read1 == 1 and passed.read2 == 1
    assert passed.properly_paired == 1
    assert passed.singleton == 1  # read c: paired, mapped, mate unmapped
    assert passed.with_mate_mapped_to_diff_chromosome == 2  # a and b
    assert passed.with_mate_mapped_to_diff_chromosome_mapq5 == 1  # only a
    assert passed.duplicates_primary.total == 1
    assert passed.duplicates_primary.cross_chromosome == 1  # mate contig -1 != 0
    assert failed.mapped == 0


# ------------------------------------------------------------------- kmers
def test_count_kmers_simple():
    recs = [
        dict(name="a", flags=4, contig_idx=-1, start=-1, mapq=255, cigar="*",
             seq="ACGTACGT", qual="I" * 8),
        dict(name="b", flags=4, contig_idx=-1, start=-1, mapq=255, cigar="*",
             seq="ACGTA", qual="I" * 5),
    ]
    b, _ = pack_reads(recs)
    counts = kmer_ops.count_kmers(b, 4)
    # brute force
    expect = {}
    for s in ["ACGTACGT", "ACGTA"]:
        for i in range(len(s) - 3):
            expect[s[i : i + 4]] = expect.get(s[i : i + 4], 0) + 1
    assert counts == expect


def test_count_kmers_with_n():
    recs = [
        dict(name="a", flags=4, contig_idx=-1, start=-1, mapq=255, cigar="*",
             seq="ACNTA", qual="IIIII"),
    ]
    b, _ = pack_reads(recs)
    counts = kmer_ops.count_kmers(b, 3)
    assert counts == {"ACN": 1, "CNT": 1, "NTA": 1}


def test_count_kmers_matches_reference_example(ref_resources):
    """k-mer counts over reads12.sam equal a pure-python sliding count."""
    from adam_tpu.io import load_alignments

    ds = load_alignments(str(ref_resources / "reads12.sam"))
    counts = ds.count_kmers(21)
    b = ds.batch.to_numpy()
    expect: dict[str, int] = {}
    for i in range(b.n_rows):
        if not b.valid[i]:
            continue
        s = schema.decode_bases(b.bases[i], int(b.lengths[i]))
        for j in range(len(s) - 20):
            w = s[j : j + 21]
            expect[w] = expect.get(w, 0) + 1
    assert counts == expect


def test_count_kmers_empty_batch():
    from adam_tpu.formats.batch import ReadBatch

    assert kmer_ops.count_kmers(ReadBatch.empty(0, 10, 2), 4) == {}
    assert kmer_ops.count_qmers(ReadBatch.empty(0, 10, 2), 4) == {}


def test_mdtag_iupac_bases():
    tag = MdTag.parse("5R10", 0)
    assert tag.mismatches == {5: "R"}
    assert tag.to_string() == "5R10"


def test_count_qmers():
    recs = [
        dict(name="a", flags=4, contig_idx=-1, start=-1, mapq=255, cigar="*",
             seq="ACGT", qual="II5I"),
    ]
    b, _ = pack_reads(recs)
    q = kmer_ops.count_qmers(b, 2)
    p40 = 1 - 10 ** -4.0
    p20 = 1 - 10 ** -2.0
    assert set(q) == {"AC", "CG", "GT"}
    np.testing.assert_allclose(q["AC"], p40 * p40, rtol=1e-12)
    np.testing.assert_allclose(q["CG"], p40 * p20, rtol=1e-12)
    np.testing.assert_allclose(q["GT"], p20 * p40, rtol=1e-12)


# ---------------------------------------------------------- smith-waterman
# End-to-end vectors from the reference's SmithWatermanSuite (:180-220).
def test_sw_simple():
    a = sw.smith_waterman("AAAA", "AAAA", 1.0, 0.0, -1.0, -1.0)
    assert a.cigar_x == "4M" and a.cigar_y == "4M"
    assert a.score == 4.0


def test_sw_indel():
    a = sw.smith_waterman("ACATGA", "ACGA", 1.0, 0.0, -0.333, -0.333)
    assert a.cigar_x == "2M2I2M"
    assert a.cigar_y == "2M2D2M"


def test_sw_snp_long():
    x = "ATTAGACTACTTAATATACAGATTTACCCCAATAGA"
    y = "ATTAGACTACTTAATATACAGAATTACCCCAATAGA"
    a = sw.smith_waterman(x, y, 1.0, 0.0, -0.333, -0.333)
    assert a.cigar_x == "36M" and a.cigar_y == "36M"


def test_sw_short_indel_long():
    x = "ATTAGACTACTTAATATACAGATTTACCCCAATAGA"
    y = "ATTAGACTACTTAATATACAGATACCCCAATAGA"
    a = sw.smith_waterman(x, y, 1.0, 0.0, -0.333, -0.333)
    assert a.cigar_x == "22M2I12M"
    assert a.cigar_y == "22M2D12M"


def test_sw_containment():
    x = "ATTAGACTACTTAATATACAGATTTACCCCAATAGA"
    y = "ACTTAATATACAGATTTACC"
    a = sw.smith_waterman(x, y, 1.0, 0.0, -0.333, -0.333)
    assert a.cigar_x == "20M"
    assert a.x_start == 8
    assert a.y_start == 0


def test_sw_batch_padded():
    """Batched alignment with different lengths under one jit shape."""
    xs = ["AAAA", "ACATGA"]
    ys = ["AAAA", "ACGA"]
    lx = max(len(s) for s in xs)
    ly = max(len(s) for s in ys)
    xc = np.stack([
        np.pad(schema.encode_bases(s), (0, lx - len(s)), constant_values=schema.BASE_PAD)
        for s in xs
    ])
    yc = np.stack([
        np.pad(schema.encode_bases(s), (0, ly - len(s)), constant_values=schema.BASE_PAD)
        for s in ys
    ])
    res = sw.smith_waterman_batch(
        xc, np.array([4, 6]), yc, np.array([4, 4]), 1.0, 0.0, -0.333, -0.333
    )
    assert res[0].cigar_x == "4M"
    assert res[1].cigar_x == "2M2I2M"


def test_sw_score_only_parity():
    """The striped score-only fills (the GCUPS path) agree with the
    trackback fill's best scores bit-for-bit — scan and Pallas
    (interpret) backends, padded variable lengths."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    B, lx, ly = 24, 31, 45
    xc = rng.integers(0, 4, (B, lx)).astype(np.int32)
    yc = rng.integers(0, 4, (B, ly)).astype(np.int32)
    xl = rng.integers(4, lx + 1, B).astype(np.int32)
    yl = rng.integers(4, ly + 1, B).astype(np.int32)
    args = (1.0, -0.333, -0.5, -0.5)
    _, bs, _ = sw._sw_fill_scan_best(
        jnp.asarray(xc), jnp.asarray(xl), jnp.asarray(yc), jnp.asarray(yl),
        *args, lx, ly,
    )
    ref = np.asarray(bs).max(axis=1)
    got_scan = np.asarray(sw.sw_best_scores(xc, xl, yc, yl, *args,
                                            backend="scan"))
    np.testing.assert_array_equal(ref, got_scan)
    got_pl = np.asarray(
        sw._sw_score_pallas(
            jnp.asarray(xc), jnp.asarray(xl), jnp.asarray(yc),
            jnp.asarray(yl), lx, ly, *args, interpret=True,
        )
    )
    np.testing.assert_array_equal(ref, got_pl)


def test_sw_score_i16_integral_weights_parity():
    """The narrow i16 score kernel (integral weight sets) matches the
    f32 scan scores exactly — integer scores are exact in both types —
    including padded variable lengths and N codes; and the router sends
    integral weights through it while rejecting fractional ones."""
    import pytest

    rng = np.random.default_rng(13)
    B, lx, ly = 40, 63, 70
    xc = rng.integers(0, 5, (B, lx)).astype(np.int32)
    yc = rng.integers(0, 5, (B, ly)).astype(np.int32)
    xl = rng.integers(4, lx + 1, B).astype(np.int32)
    yl = rng.integers(4, ly + 1, B).astype(np.int32)
    args = (2.0, -1.0, -1.0, -1.0)
    ref = np.asarray(sw.sw_best_scores(xc, xl, yc, yl, *args,
                                       backend="scan"))
    got = np.asarray(
        sw._sw_score_pallas(
            jnp.asarray(xc), jnp.asarray(xl), jnp.asarray(yc),
            jnp.asarray(yl), lx, ly, *args, interpret=True,
            dtype_name="i16",
        )
    )
    np.testing.assert_array_equal(ref, got)
    with pytest.raises(ValueError):
        sw._sw_score_pallas(
            jnp.asarray(xc), jnp.asarray(xl), jnp.asarray(yc),
            jnp.asarray(yl), lx, ly, 1.0, -0.333, -0.5, -0.5,
            interpret=True, dtype_name="i16",
        )
    with pytest.raises(ValueError):
        sw.sw_best_scores(xc, xl, yc, yl, 1.0, -0.333, -0.5, -0.5,
                          backend="pallas_i16")


def test_sw_score_long_reads_multi_tile():
    """Long-read shapes: lx past one 128-lane tile (L=256 sublane
    state, 9-step delete chains) agrees across backends, N codes
    included.  (Multi-grid-tile batches run on the real chip in
    benchmark_gcups; interpret mode keeps this test single-tile.)"""
    rng = np.random.default_rng(11)
    B, lx, ly = 300, 250, 310
    xc = rng.integers(0, 5, (B, lx)).astype(np.int32)  # incl. N codes
    yc = rng.integers(0, 5, (B, ly)).astype(np.int32)
    xl = rng.integers(40, lx + 1, B).astype(np.int32)
    yl = rng.integers(60, ly + 1, B).astype(np.int32)
    args = (1.0, -0.333, -0.5, -0.5)
    got_scan = np.asarray(sw.sw_best_scores(xc, xl, yc, yl, *args,
                                            backend="scan"))
    got_pl = np.asarray(
        sw._sw_score_pallas(
            jnp.asarray(xc), jnp.asarray(xl), jnp.asarray(yc),
            jnp.asarray(yl), lx, ly, *args, interpret=True,
        )
    )
    np.testing.assert_array_equal(got_scan, got_pl)


# ------------------------------------------------------------------ mdtag
def test_mdtag_parse_and_tostring_roundtrip():
    for md in ["75", "10A5", "0A74", "10^AC5", "5A0C5", "0C0C10", "10^AC0T5"]:
        tag = MdTag.parse(md, 100)
        assert tag.to_string() == md, md


def test_mdtag_parse_structure():
    tag = MdTag.parse("10A5^GG3", 0)
    assert tag.is_match(5) and not tag.is_match(10)
    assert tag.mismatches == {10: "A"}
    assert tag.deletions == {16: "G", 17: "G"}
    assert tag.end() == 20


def test_mdtag_from_alignment():
    #       read:  ACGTACGT  ref: ACGAACGT -> mismatch at pos 3
    tag = MdTag.from_alignment("ACGTACGT", "ACGAACGT", "8M", 0)
    assert tag.to_string() == "3A4"
    # deletion: read ACGTGT vs ref ACGTAAGT cigar 4M2D2M
    tag = MdTag.from_alignment("ACGTGT", "ACGTAAGT", "4M2D2M", 0)
    assert tag.to_string() == "4^AA2"
    # insertion consumes read only
    tag = MdTag.from_alignment("ACGTTTGT", "ACGTGT", "4M2I2M", 0)
    assert tag.to_string() == "6"


def test_mdtag_get_reference():
    tag = MdTag.parse("4^AA2", 10)
    assert tag.get_reference("ACGTGT", "4M2D2M") == "ACGTAAGT"
    tag = MdTag.parse("3A4", 0)
    assert tag.get_reference("ACGTACGT", "8M") == "ACGAACGT"
    # corrupt alignment: CIGAR span overruns the read -> loud failure,
    # not a silently truncated reference
    with pytest.raises(IndexError):
        MdTag.parse("12", 0).get_reference("ACGT", "12M")


def test_mdtag_move_alignment():
    # realign same read against a shifted reference
    tag = MdTag.move_alignment("ACGTAAGT", "ACGTGT", "4M2D2M", 50)
    assert tag.to_string() == "4^AA2"
    assert tag.start == 50


def test_batch_md_arrays():
    recs = [
        dict(name="a", flags=0, contig_idx=0, start=10, mapq=60, cigar="4M",
             seq="ACGT", qual="IIII", md="2G1"),
        dict(name="b", flags=0, contig_idx=0, start=20, mapq=60, cigar="2M2I2M",
             seq="ACTTGT", qual="IIIIII", md="4"),
    ]
    b, side = pack_reads(recs)
    is_mm, ref_codes, has_md = batch_md_arrays(b, side)
    np.testing.assert_array_equal(is_mm[0, :4], [False, False, True, False])
    assert schema.decode_bases(ref_codes[0], 4) == "ACGT".replace("G", "G")[:2] + "G" + "T"
    # insertion positions have no reference base
    np.testing.assert_array_equal(ref_codes[1, 2:4], [schema.BASE_PAD] * 2)
    assert has_md.all()


def test_batch_md_arrays_matches_oracle():
    """Differential: vectorized MD path == per-read oracle on tricky MDs."""
    import numpy as np

    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.ops.mdtag import batch_md_arrays_reference

    rng = np.random.default_rng(7)
    recs = []
    cases = [
        ("4M", "ACGT", "4"),                    # all match
        ("4M", "ACGT", "0A3"),                  # leading 0 run
        ("4M", "ACGT", "3A0"),                  # trailing 0 run
        ("4M", "ACGT", "1A0C1"),                # adjacent mismatches
        ("2M2D2M", "ACGT", "2^TT2"),            # deletion
        ("2S4M", "TTACGT", "2A1"),              # soft clip
        ("2M3I2M", "ACTTTGT", "1G2"),           # insertion
        ("1S2M1D2M1S", "AACGTC", "2^G0A1"),     # everything at once
        ("6M", "ACGTAC", "0A0C0G0T0A0C0"),      # all mismatch
    ]
    for i, (cig, seq, md) in enumerate(cases):
        recs.append(dict(name=f"r{i}", flags=0, contig_idx=0, start=10 + i,
                         mapq=60, cigar=cig, seq=seq, qual="I" * len(seq),
                         md=md))
    # plus random simple reads, some without MD
    for i in range(40):
        L = int(rng.integers(3, 12))
        seq = "".join(rng.choice(list("ACGT"), L))
        md = None if i % 5 == 0 else str(L)
        recs.append(dict(name=f"q{i}", flags=0, contig_idx=0, start=i,
                         mapq=60, cigar=f"{L}M", seq=seq, qual="I" * L,
                         md=md))
    b, side = pack_reads(recs)
    got = batch_md_arrays(b, side)
    want = batch_md_arrays_reference(b, side)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])
    np.testing.assert_array_equal(got[2], want[2])


def test_batch_md_arrays_empty_batch():
    from adam_tpu.formats.batch import ReadBatch, ReadSidecar

    b = ReadBatch.empty()
    is_mm, ref_codes, has_md = batch_md_arrays(b, ReadSidecar())
    assert is_mm.shape[0] == 0 and has_md.shape[0] == 0


def test_sw_pallas_interpret_parity():
    """The Pallas wavefront kernel must produce the scan fill's scores and
    moves bit-for-bit (interpret mode runs the kernel on CPU)."""
    rng = np.random.default_rng(7)
    B, lx, ly = 9, 37, 29
    xc = rng.integers(0, 4, (B, lx)).astype(np.int32)
    yc = rng.integers(0, 4, (B, ly)).astype(np.int32)
    xl = rng.integers(1, lx + 1, B).astype(np.int32)
    yl = rng.integers(1, ly + 1, B).astype(np.int32)
    args = (1.0, -0.333, -0.5, -0.5)

    m_scan, bs_scan, bd_scan = sw._sw_fill_scan_best(
        jnp.asarray(xc), jnp.asarray(xl), jnp.asarray(yc), jnp.asarray(yl),
        *args, lx, ly,
    )
    m_pl, bs_pl, bd_pl = sw._sw_fill_pallas(
        jnp.asarray(xc), jnp.asarray(xl), jnp.asarray(yc), jnp.asarray(yl),
        lx, ly, *args, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(m_pl), np.asarray(m_scan))
    np.testing.assert_array_equal(np.asarray(bs_pl), np.asarray(bs_scan))
    # the winning diagonal only matters where a real (finite) best exists
    finite = np.isfinite(np.asarray(bs_scan))
    np.testing.assert_array_equal(
        np.asarray(bd_pl)[finite], np.asarray(bd_scan)[finite]
    )
