"""``adam-tpu top`` dashboard (utils/top.py): heartbeat parsing (torn
lines, both schema versions), frame rendering, and the follow loop's
exit contract (0 on done+ok, 1 on done+!ok, 2 on no stream)."""

import io
import json
import os
import time

from adam_tpu.cli.main import main
from adam_tpu.utils import telemetry as tele
from adam_tpu.utils import top as top_mod


def _line(**over):
    base = {
        "schema": tele.HEARTBEAT_SCHEMA,
        "seq": 0,
        "elapsed_s": 1.5,
        "windows_ingested": 2,
        "windows_total": 4,
        "windows_resumed": 1,
        "parts_written": 1,
        "reads_ingested": 5000,
        "reads_per_s": 3333.3,
        "bytes_written": 1 << 20,
        "h2d_bytes": 10 << 20,
        "d2h_bytes": 5 << 20,
        "hbm_bytes_in_use": {"0": 1 << 30},
        "hbm_peak_bytes": 2 << 30,
        "inflight": 2,
        "inflight_per_device": {"0": 1, "1": 1},
        "retries": 3,
        "faults": 1,
        "devices_evicted": 0,
        "eta_s": 4.5,
        "done": False,
        "ok": True,
    }
    base.update(over)
    return base


def test_parse_ignores_torn_tail_and_junk():
    good = json.dumps(_line(seq=0)) + "\n" + json.dumps(_line(seq=1)) + "\n"
    text = good + "not json\n" + json.dumps(_line(seq=2))  # no newline
    lines = top_mod.parse_heartbeat_text(text)
    # the junk line stops nothing, the unterminated tail is deferred
    assert [l["seq"] for l in lines] == [0, 1]
    # a /1 line (no ledger fields) still parses
    v1 = {k: v for k, v in _line().items()
          if k not in ("h2d_bytes", "d2h_bytes", "hbm_bytes_in_use",
                       "hbm_peak_bytes")}
    v1["schema"] = "adam_tpu.heartbeat/1"
    assert top_mod.parse_heartbeat_text(json.dumps(v1) + "\n")


def test_render_frame_contents():
    text = top_mod.render_frame(_line(), source="hb.ndjson")
    assert "RUNNING" in text
    assert "2/4" in text and "resumed 1" in text and "parts 1" in text
    assert "5,000" in text
    assert "10.0MiB" in text and "5.0MiB" in text  # tunnel totals
    assert "1.0GiB" in text and "peak 2.0GiB" in text
    assert "retries 3" in text and "faults 1" in text
    done = top_mod.render_frame(_line(done=True, ok=True))
    assert "DONE" in done and "run complete" in done
    failed = top_mod.render_frame(_line(done=True, ok=False))
    assert "FAILED" in failed and "ok=false" in failed
    # /1 line without HBM fields: no fabricated zeros, no crash
    v1 = {k: v for k, v in _line().items()
          if not k.startswith(("h2d", "d2h", "hbm"))}
    text = top_mod.render_frame(v1)
    assert "h2d -" in text and "hbm" not in text.splitlines()[5]


def test_follow_exit_codes(tmp_path, capsys):
    p = str(tmp_path / "hb.ndjson")
    with open(p, "w") as fh:
        fh.write(json.dumps(_line(seq=0)) + "\n")
        fh.write(json.dumps(_line(seq=1, done=True, ok=True)) + "\n")
    out = io.StringIO()
    assert top_mod.follow(p, interval=0.01, out=out) == 0
    assert "DONE" in out.getvalue()
    # crashed run: final line ok=false -> exit 1
    with open(p, "w") as fh:
        fh.write(json.dumps(_line(done=True, ok=False)) + "\n")
    assert top_mod.follow(p, interval=0.01, out=io.StringIO()) == 1
    # missing file in -once mode -> exit 2
    assert top_mod.follow(str(tmp_path / "nope.ndjson"), once=True,
                          out=io.StringIO()) == 2
    # empty file in -once mode -> exit 2
    empty = str(tmp_path / "empty.ndjson")
    open(empty, "w").close()
    assert top_mod.follow(empty, once=True, out=io.StringIO()) == 2
    # live (not done) stream in -once mode renders one frame, exit 0
    live = str(tmp_path / "live.ndjson")
    with open(live, "w") as fh:
        fh.write(json.dumps(_line(seq=0)) + "\n")
    out = io.StringIO()
    assert top_mod.follow(live, once=True, out=out) == 0
    assert "RUNNING" in out.getvalue()


def test_follow_survives_rotation_truncate(tmp_path):
    """A file that shrinks (the heartbeat rotated it) re-reads from the
    top instead of wedging on a stale offset."""
    p = str(tmp_path / "hb.ndjson")
    big = json.dumps(_line(seq=0, reads_ingested=10**9)) + "\n"
    with open(p, "w") as fh:
        fh.write(big * 5)
    out = io.StringIO()
    assert top_mod.follow(p, once=True, out=out) == 0
    # simulate rotation: much smaller fresh file carrying the final line
    with open(p, "w") as fh:
        fh.write(json.dumps(_line(seq=9, done=True)) + "\n")
    assert top_mod.follow(p, interval=0.01, out=io.StringIO()) == 0


def test_top_cli_subcommand(tmp_path, capsys):
    p = str(tmp_path / "hb.ndjson")
    with open(p, "w") as fh:
        fh.write(json.dumps(_line(done=True)) + "\n")
    assert main(["top", p, "-once"]) == 0
    assert "adam-tpu top" in capsys.readouterr().out
    assert main(["top", str(tmp_path / "missing"), "-once"]) == 2


# ---------------------------------------------------------------------------
# Multi-job view (serve run-root aggregation)
# ---------------------------------------------------------------------------
def _job_stream(root, job, *lines):
    d = root / job
    d.mkdir(parents=True, exist_ok=True)
    with open(d / "heartbeat.ndjson", "w") as fh:
        for ln in lines:
            fh.write(json.dumps(ln) + "\n")


def test_render_multi_frame_sums_and_states(tmp_path):
    """Per-job rows + summed job-scoped totals; JOB.json states win
    over the heartbeat's done/ok heuristic."""
    jobs = {
        "a": _line(done=True, ok=True, parts_written=3,
                   bytes_written=100, reads_ingested=10),
        "b": _line(done=False, parts_written=2, bytes_written=50,
                   reads_ingested=5, reads_per_s=7.0),
        "c": _line(done=True, ok=False, parts_written=0),
    }
    frame = top_mod.render_multi_frame(
        jobs, root="R", states={"c": "interrupted"},
        pool={"h2d_bytes": 9 << 20, "d2h_bytes": 1 << 20,
              "retries": 4, "faults": 2},
    )
    assert "multi-job R" in frame and "3 job(s)" in frame
    assert "DONE" in frame and "RUNNING" in frame
    assert "INTERRUPTED" in frame and "FAILED" not in frame
    assert "parts 5" in frame  # 3 + 2 + 0 summed
    assert "1 running  1 done  1 stopped/failed" in frame
    assert "retries 4" in frame and "faults 2" in frame


def test_follow_root_exit_codes_and_midwatch_join(tmp_path):
    root = tmp_path / "run-root"
    root.mkdir()
    # no streams yet: bounded wait exits 2
    assert top_mod.follow_root(
        str(root), interval=0.01, once=True, out=io.StringIO()
    ) == 2
    # two jobs, all done+ok -> 0 (the service's own stream at the root
    # is pool totals, not a job; done=true = the scheduler closed)
    _job_stream(root, "jobA", _line(seq=0), _line(seq=1, done=True))
    _job_stream(root, "jobB", _line(seq=0, done=True))
    with open(root / "heartbeat.ndjson", "w") as fh:
        fh.write(json.dumps(_line(seq=0, done=True)) + "\n")
    out = io.StringIO()
    assert top_mod.follow_root(str(root), interval=0.01, out=out) == 0
    txt = out.getvalue()
    assert "jobA" in txt and "jobB" in txt
    assert "2 job(s)" in txt
    # one job ends ok=false -> 1 (a genuine failure)
    _job_stream(root, "jobB", _line(seq=0, done=True, ok=False))
    assert top_mod.follow_root(
        str(root), interval=0.01, out=io.StringIO()
    ) == 1
    # ...but ok=false from a graceful drain (durable JOB.json says
    # interrupted) is a clean stop, not a failure -> 0
    (root / "jobB").mkdir(exist_ok=True)
    with open(root / "jobB" / "JOB.json", "w") as fh:
        json.dump({"state": "interrupted"}, fh)
    assert top_mod.follow_root(
        str(root), interval=0.01, out=io.StringIO()
    ) == 0
    os.unlink(root / "jobB" / "JOB.json")
    # a job appearing mid-watch joins the board before exit; a LIVE
    # service stream keeps the watch open even with every discovered
    # job done (capacity-queued jobs may have no stream yet)
    import threading

    with open(root / "heartbeat.ndjson", "w") as fh:
        fh.write(json.dumps(_line(seq=0)) + "\n")  # service live again

    def late_join():
        time.sleep(0.15)
        _job_stream(root, "jobC", _line(seq=0, done=True))
        _job_stream(root, "jobB", _line(seq=1, done=True))
        with open(root / "heartbeat.ndjson", "a") as fh:
            fh.write(json.dumps(_line(seq=1, done=True)) + "\n")

    _job_stream(root, "jobB", _line(seq=0))  # live again
    t = threading.Thread(target=late_join)
    t.start()
    out = io.StringIO()
    assert top_mod.follow_root(
        str(root), interval=0.02, out=out, max_wait_s=30
    ) == 0
    t.join()
    assert "jobC" in out.getvalue()


def test_top_cli_multi_job_directory(tmp_path, capsys):
    root = tmp_path / "serve-root"
    _job_stream(root, "j1", _line(done=True))
    assert main(["top", str(root), "-once"]) == 0
    assert "multi-job" in capsys.readouterr().out


def test_render_frame_slo_cell():
    # /7 producer with an armed SLO engine: worst burn + regressions
    text = top_mod.render_frame(
        _line(slo_worst_burn=14.4, perf_regressions=2),
        source="hb.ndjson")
    assert "burn 14.4x" in text
    assert "perf regressions 2" in text
    # regressions without an SLO engine still renders the cell
    text = top_mod.render_frame(
        _line(slo_worst_burn=None, perf_regressions=1),
        source="hb.ndjson")
    assert "no slo" in text and "perf regressions 1" in text
    # a pre-/7 producer (no fields at all) renders no slo cell
    text = top_mod.render_frame(_line(), source="hb.ndjson")
    assert "slo  " not in text


def test_cli_once_long_flag_and_exit_codes(tmp_path, capsys):
    p = str(tmp_path / "hb.ndjson")
    with open(p, "w") as fh:
        fh.write(json.dumps(_line(slo_worst_burn=2.0)) + "\n")
    assert main(["top", p, "--once"]) == 0  # long spelling
    assert "burn 2.0x" in capsys.readouterr().out
    with open(p, "a") as fh:
        fh.write(json.dumps(_line(seq=1, done=True, ok=False)) + "\n")
    assert main(["top", p, "--once"]) == 1
    capsys.readouterr()
    assert main(["top", str(tmp_path / "absent.ndjson"), "--once"]) == 2
