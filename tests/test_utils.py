"""Util-layer tests: TwoBitFile (golden vs reference TwoBitSuite),
attributes (AttributeUtilsSuite), interval lists (IntervalListReaderSuite),
DNAPrefixTrie (DNAPrefixTrieSuite), flattener, instrumentation."""

import numpy as np
import pytest

from adam_tpu.ops.prefix_trie import DNAPrefixTrie
from adam_tpu.utils.attributes import TagType, parse_attribute, parse_attributes
from adam_tpu.utils.interval_list import IntervalListReader
from adam_tpu.utils.two_bit import TwoBitFile


class TestTwoBit:
    def test_hg19_chrM_golden(self, ref_resources):
        """Same expectations as TwoBitSuite.scala:27-37."""
        tb = TwoBitFile(str(ref_resources / "hg19.chrM.2bit"))
        assert tb.num_seq == 1
        assert tb.extract("hg19_chrM", 0, 10) == "GATCACAGGT"
        assert tb.extract("hg19_chrM", 503, 513) == "CATCCTACCC"
        assert tb.extract("hg19_chrM", 16561, 16571) == "CATCACGATG"

    def test_out_of_bounds(self, ref_resources):
        tb = TwoBitFile(str(ref_resources / "hg19.chrM.2bit"))
        size = tb.records["hg19_chrM"].dna_size
        with pytest.raises(ValueError):
            tb.extract("hg19_chrM", 0, size + 1)
        assert len(tb.extract("hg19_chrM", 0, size)) == size

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            TwoBitFile(b"\x00" * 32)


class TestAttributes:
    def test_parse_tags(self):
        tags = parse_attributes("XT:i:3\tXU:Z:foo,bar")
        assert len(tags) == 2
        assert tags[0].tag == "XT"
        assert tags[0].tag_type is TagType.INTEGER
        assert tags[0].value == 3
        assert tags[1].tag == "XU"
        assert tags[1].tag_type is TagType.STRING
        assert tags[1].value == "foo,bar"

    def test_empty_string(self):
        assert parse_attributes("") == []

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            parse_attribute("XT:i")

    def test_string_with_colon(self):
        s = "a:b:c:d"
        tags = parse_attributes("XX:Z:" + s)
        assert len(tags) == 1 and tags[0].value == s

    def test_numeric_sequence_roundtrip(self):
        a = parse_attribute("XB:B:i,1,2,3")
        assert a.tag_type is TagType.NUMERIC_SEQUENCE
        assert a.value == ("i", [1, 2, 3])
        assert str(a) == "XB:B:i,1,2,3"

    def test_str_roundtrip(self):
        for s in ("XT:i:3", "XU:Z:foo,bar", "XA:A:c", "XF:f:1.5"):
            assert str(parse_attribute(s)) == s


class TestIntervalList:
    def test_gatk_example(self, ref_resources):
        """IntervalListReaderSuite expectations, shifted to 0-based
        half-open coordinates."""
        reader = IntervalListReader(
            str(ref_resources / "example_intervals.list")
        )
        intervals = list(reader)
        assert len(intervals) == 6
        for idx in range(1, 7):
            assert intervals[idx - 1][1] == f"target_{idx}"
        # first row is 1:30366-30503 (1-based incl) -> [30365, 30503)
        region = intervals[0][0]
        assert (region.referenceName, region.start, region.end) == (
            "1", 30365, 30503,
        )
        sd = reader.sequence_dictionary
        assert len(sd.records) == 2
        assert sd["1"].length == 249250621
        assert sd["2"].length == 243199373


class TestDNAPrefixTrie:
    def test_empty_rejected(self):
        with pytest.raises(AssertionError):
            DNAPrefixTrie({})

    def test_full_wildcard(self):
        trie = DNAPrefixTrie({"AA": 1, "TT": 2, "CC": 3})
        assert trie.size == 3
        assert len(trie.find("**")) == 3

    def test_illegal_characters(self):
        with pytest.raises(ValueError):
            DNAPrefixTrie({"ATMGC": 0})

    def test_ambiguous_keys_dropped(self):
        trie = DNAPrefixTrie({"ANCT": 0.5, "ACTN": 1.0})
        assert trie.size == 0
        assert not trie.contains("ANCT")
        assert not trie.contains("ACTN")

    def test_mixed_lengths_rejected(self):
        with pytest.raises(AssertionError):
            DNAPrefixTrie({"ACTCGA": 1.2, "ACTCA": 1.1})

    def test_insert_and_get(self):
        trie = DNAPrefixTrie({"ACCTA": 1, "ACTGA": 2, "CCTCA": 3})
        assert trie.size == 3
        for k, v in [("ACCTA", 1), ("ACTGA", 2), ("CCTCA", 3)]:
            assert trie.contains(k)
            assert trie.get(k) == v
        assert trie.get_or_else("TTTTT", 9) == 9
        assert trie.get_if_exists("TTTTT") is None
        with pytest.raises(KeyError):
            trie.get("TTTTT")

    sample = {
        "AACACT": 1, "AACACC": 4, "ATGGTC": 2, "CACTGC": 5,
        "CCTCGA": 4, "GGCGTC": 6, "TCCTCG": 4, "TTCTTC": 2,
    }

    def test_wildcard_search(self):
        found = DNAPrefixTrie(self.sample).search("A****C")
        assert found == {"AACACC": 4, "ATGGTC": 2}

    def test_prefix_search(self):
        found = DNAPrefixTrie(self.sample).prefix_search("AACA")
        assert found == {"AACACT": 1, "AACACC": 4}

    def test_suffix_search(self):
        found = DNAPrefixTrie(self.sample).suffix_search("TC")
        assert found == {"ATGGTC": 2, "GGCGTC": 6, "TTCTTC": 2}


class TestInstrumentation:
    def test_timer_report(self):
        from adam_tpu.utils.instrumentation import TimerRegistry

        reg = TimerRegistry()
        reg.recording = True
        with reg.time("Stage A"):
            pass
        with reg.time("Stage A"):
            pass
        rep = reg.report()
        assert "Stage A" in rep and "2" in rep

    def test_disabled_registry_records_nothing(self):
        from adam_tpu.utils.instrumentation import TimerRegistry

        reg = TimerRegistry()
        with reg.time("Stage A"):
            pass
        assert reg.timers == {}


class TestReviewRegressions:
    def test_a_type_must_be_single_char(self):
        with pytest.raises(ValueError):
            parse_attribute("XT:A:")
        with pytest.raises(ValueError):
            parse_attribute("XT:A:AB")

    def test_trie_depth_cap(self):
        with pytest.raises(ValueError):
            DNAPrefixTrie({"T" * 32: 1})
        t = DNAPrefixTrie({"T" * 31: 1})
        assert t.contains("T" * 31)

    def test_genotype_sort_on_save(self, ref_resources, tmp_path):
        from adam_tpu.api.datasets import GenotypeDataset

        gt = GenotypeDataset.load(str(ref_resources / "small.vcf"))
        srt = gt.sorted_by_position()
        key = list(
            zip(srt.variants.contig_idx.tolist(), srt.variants.start.tolist())
        )
        assert key == sorted(key)
        # genotype links survive the permutation
        for g_i in range(len(srt.genotypes)):
            vi = int(srt.genotypes.variant_idx[g_i])
            assert 0 <= vi < len(srt.variants)
        out = tmp_path / "gt.adam"
        gt.save(str(out), sort_on_save=True)
        rt = GenotypeDataset.load(str(out))
        key2 = list(
            zip(rt.variants.contig_idx.tolist(), rt.variants.start.tolist())
        )
        assert key2 == sorted(key2)
