"""Multi-chip device pool: round-robin parity, prewarm, plumbing.

The 8 virtual CPU devices (tests/conftest.py backend trick) stand in
for an 8-chip topology: the streamed flagship with ``devices=4`` must
produce **bit-identical** output to ``devices=1`` — Parquet part
contents, recalibration table, flagstat — because every merge is a
host-side sum over per-window parts in window order (the pool changes
WHERE work runs, never what it computes).  Prewarm must compile every
grid-quantized kernel shape exactly once per device, concurrently, and
never twice per process.
"""

import hashlib
import os
import sys

import numpy as np
import pytest

from adam_tpu.parallel import device_pool as dp
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


# ---------------------------------------------------------------------------
# Device-count resolution
# ---------------------------------------------------------------------------
def test_resolve_device_count_env_and_cap(monkeypatch):
    import jax

    attached = len(jax.devices())
    assert attached == 8  # the conftest virtual mesh this suite assumes
    monkeypatch.delenv("ADAM_TPU_DEVICES", raising=False)
    assert dp.resolve_device_count() == attached
    monkeypatch.setenv("ADAM_TPU_DEVICES", "3")
    assert dp.resolve_device_count() == 3
    # explicit arg beats env; beyond-topology requests cap, not raise
    assert dp.resolve_device_count(2) == 2
    assert dp.resolve_device_count(attached + 5) == attached
    # malformed env values degrade (warn + all attached); only the
    # explicit CLI arg is a hard error
    for bad in ("not-an-int", "0", "-3"):
        monkeypatch.setenv("ADAM_TPU_DEVICES", bad)
        assert dp.resolve_device_count() == attached
    with pytest.raises(ValueError, match="devices"):
        dp.resolve_device_count(0)


def test_make_pool_single_device_falls_back():
    assert dp.make_pool(1) is None
    pool = dp.make_pool(4)
    assert pool is not None and pool.n == 4
    # round-robin: window i -> device i % n
    assert [pool.device_index(i) for i in range(6)] == [0, 1, 2, 3, 0, 1]
    assert pool.device(5) is pool.devices[1]


def test_pool_put_commits_to_round_robin_device():
    import jax

    pool = dp.DevicePool(limit=3)
    for i in range(4):
        arr = pool.put(np.arange(8), i)
        (dev,) = arr.devices()
        assert dev == pool.device(i)
    jax.block_until_ready(arr)


# ---------------------------------------------------------------------------
# Prewarm: exactly once per (kernel shape, device), process-wide
# ---------------------------------------------------------------------------
def test_prewarm_compiles_each_shape_once_per_device():
    dp.reset_prewarm_cache()
    try:
        pool = dp.DevicePool(limit=4)
        calls: list = []

        def make(key):
            def fn(dev):
                calls.append((key, dev.id))
            return (key, fn)

        entries = [make(("k1", 1024, 128)), make(("k2", 1024, 128))]
        tr = tele.Tracer(recording=True)
        n = pool.prewarm(entries, tracer=tr)
        assert n == 2 * pool.n
        # every (entry, device) pair exactly once
        assert sorted(calls) == sorted(
            (key, d.id) for key, _fn in entries for d in pool.devices
        )
        # per-compile spans carry device attribution into the tracer
        snap = tr.snapshot()
        assert snap["spans"][tele.SPAN_POOL_PREWARM_COMPILE]["count"] == n
        assert set(
            snap["device_spans"][tele.SPAN_POOL_PREWARM_COMPILE]
        ) == {str(k) for k in range(pool.n)}
        assert snap["counters"][tele.C_POOL_PREWARM_COMPILES] == n

        # second prewarm in the same process: nothing to do (the bench's
        # warmup-run-then-timed-run pattern relies on this)
        calls.clear()
        assert pool.prewarm(entries, tracer=tr) == 0
        assert calls == []
        # a second pool over the same devices is also already warm
        assert dp.DevicePool(limit=4).prewarm(entries, tracer=tr) == 0
        # ... but a device the first pool didn't cover is not
        assert dp.DevicePool(limit=5).prewarm(entries, tracer=tr) == 2
    finally:
        dp.reset_prewarm_cache()


def test_prewarm_failure_degrades_and_stays_retryable():
    """A failed compile must not abort the run (prewarm is an
    optimization) and must discard its claim so a later prewarm
    retries it."""
    dp.reset_prewarm_cache()
    try:
        pool = dp.DevicePool(limit=2)
        attempts: list = []
        fail_next = [True]

        def fn(dev):
            attempts.append(dev.id)
            if fail_next[0]:
                raise RuntimeError("transient compile RPC failure")

        entries = [(("flaky", 1, 1), fn)]
        assert pool.prewarm(entries) == 0  # both compiles failed, no raise
        assert sorted(attempts) == [0, 1]
        fail_next[0] = False
        assert pool.prewarm(entries) == 2  # claims were discarded: retried
        assert pool.prewarm(entries) == 0  # now warm
    finally:
        dp.reset_prewarm_cache()


def test_streamed_prewarm_entries_cover_enabled_kernels():
    from adam_tpu.formats.batch import pack_reads

    recs = [
        dict(name=f"r{i}", flags=0, contig_idx=0, start=100 + i, mapq=60,
             cigar="10M", seq="ACGTACGTAC", qual="I" * 10, read_group_idx=0)
        for i in range(4)
    ]
    batch, _side = pack_reads(recs)
    b = batch.to_numpy()
    keys = [k[0] for k, _fn in dp.streamed_prewarm_entries(b, 2)]
    assert keys == ["markdup.columns", "bqsr.observe", "bqsr.apply"]
    keys = [
        k[0] for k, _fn in dp.streamed_prewarm_entries(
            b, 2, mark_duplicates=False
        )
    ]
    assert keys == ["bqsr.observe", "bqsr.apply"]
    assert dp.streamed_prewarm_entries(
        b, 2, mark_duplicates=False, recalibrate=False
    ) == []


def test_streamed_prewarm_entries_execute():
    """The dummy-arg warm calls really compile+run the kernel set (shape
    or dtype drift between prewarm and the real dispatches would show up
    here as a trace error)."""
    from adam_tpu.formats.batch import pack_reads

    recs = [
        dict(name=f"r{i}", flags=0, contig_idx=0, start=100 + i, mapq=60,
             cigar="10M", seq="ACGTACGTAC", qual="I" * 10, read_group_idx=0)
        for i in range(4)
    ]
    batch, _side = pack_reads(recs)
    dp.reset_prewarm_cache()
    try:
        pool = dp.DevicePool(limit=2)
        entries = dp.streamed_prewarm_entries(batch.to_numpy(), 2)
        assert pool.prewarm(entries) == len(entries) * 2
    finally:
        dp.reset_prewarm_cache()


# ---------------------------------------------------------------------------
# Streamed multi-device parity: bit-identical to the single-device run
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def parity_runs(tmp_path_factory):
    """One streamed run per device count over the same WGS-shaped input,
    pinned to the device backend on the virtual mesh."""
    from make_wgs_sam import make_wgs

    from adam_tpu.pipelines.streamed import transform_streamed

    d = tmp_path_factory.mktemp("device_pool")
    path = str(d / "in.sam")
    make_wgs(path, 2048, 100, n_contigs=2, contig_len=30_000,
             indel_every=800, snp_every=400)
    os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
    runs = {}
    try:
        for n in (1, 4):
            out = str(d / f"out{n}.adam")
            csv = str(d / f"obs{n}.csv")
            stats = transform_streamed(
                path, out, window_reads=512, devices=n,
                dump_observations=csv,
            )
            runs[n] = (out, csv, stats)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    return runs


def test_streamed_device_pool_parts_bit_identical(parity_runs):
    """Every Parquet part file is byte-identical between devices=1 and
    devices=4 — same windows, same flags/quals/sidecars, same encode."""
    out1, _, stats1 = parity_runs[1]
    out4, _, stats4 = parity_runs[4]
    assert stats1["n_devices"] == 1 and stats4["n_devices"] == 4
    parts1 = sorted(f for f in os.listdir(out1) if f.startswith("part-"))
    parts4 = sorted(f for f in os.listdir(out4) if f.startswith("part-"))
    assert parts1 == parts4 and parts1
    for f in parts1:
        h1 = hashlib.sha256(
            open(os.path.join(out1, f), "rb").read()
        ).hexdigest()
        h4 = hashlib.sha256(
            open(os.path.join(out4, f), "rb").read()
        ).hexdigest()
        assert h1 == h4, f


def test_streamed_device_pool_recal_table_identical(parity_runs):
    """The merged observation table (the recalibration table's source of
    truth) is identical: per-device histograms merged host-side in
    window order cannot drift from the single-device sum."""
    _, csv1, _ = parity_runs[1]
    _, csv4, _ = parity_runs[4]
    t1 = open(csv1).read()
    assert t1 == open(csv4).read()
    assert len(t1.splitlines()) > 1  # a real table, not an empty header


def test_streamed_device_pool_flagstat_identical(parity_runs):
    from adam_tpu.io import context
    from adam_tpu.ops.flagstat import format_flagstat

    out1, _, _ = parity_runs[1]
    out4, _, _ = parity_runs[4]
    fs1 = format_flagstat(*context.load_alignments(out1).flagstat())
    fs4 = format_flagstat(*context.load_alignments(out4).flagstat())
    assert fs1 == fs4
    assert "in total" in fs1


def test_streamed_device_pool_telemetry(parity_runs):
    """The pool run reports its fan-out: n_devices in the stats dict
    and the prewarm umbrella wall in the derived view (disjoint from
    pass A's row — the stage walls must still sum to the pipeline
    wall, not double-count the compile time)."""
    _, _, stats = parity_runs[4]
    assert stats["n_devices"] == 4
    assert stats["prewarm_s"] > 0
    assert stats["ingest_pass_s"] >= 0
    # the umbrella is wall time: it fits inside the total, which the
    # sum of concurrent per-compile spans generally would not
    assert stats["prewarm_s"] <= stats["total_s"]


def test_chrome_trace_mirrors_device_tracks():
    """Device-attributed spans land on one ``device:<k>`` track per chip
    next to their host-thread track."""
    tr = tele.Tracer(recording=True)
    with tr.span(tele.SPAN_APPLY_DISPATCH, window=0, device=2):
        pass
    with tr.span(tele.SPAN_APPLY_DISPATCH, window=1, device=5):
        pass
    with tr.span(tele.SPAN_SOLVE):
        pass
    doc = tr.to_chrome_trace()
    names = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e.get("ph") == "M"
    }
    assert {"device:2", "device:5"} <= names
    dev_events = [
        e for e in doc["traceEvents"]
        if e.get("ph") == "X" and (e.get("args") or {}).get("device") == 2
    ]
    # once on the host thread track, mirrored once on the device track
    assert len(dev_events) == 2
    assert len({e["tid"] for e in dev_events}) == 2
    # per-device aggregates ride the snapshot for occupancy/skew reports
    snap = tr.snapshot()
    per = snap["device_spans"][tele.SPAN_APPLY_DISPATCH]
    assert set(per) == {"2", "5"}
    assert per["2"]["count"] == 1


# ---------------------------------------------------------------------------
# Eviction attribution: replayed work vs organic occupancy
# ---------------------------------------------------------------------------
def test_span_attrs_mark_replay_scope():
    """Inside a replay_scope every device-attributed span picks up
    ``replay=1`` — in any layer, with no API plumbing — so the
    ``device_spans`` aggregation can keep a survivor's replay burden
    apart from its organic work."""
    import jax

    dev = jax.devices()[0]
    base = dp.span_attrs(dev)
    assert "replay" not in base
    with dp.replay_scope():
        marked = dp.span_attrs(dev)
        assert marked["device"] == base["device"]
        assert marked["replay"] == 1
        with dp.replay_scope():  # reentrant
            assert dp.span_attrs(dev)["replay"] == 1
        assert dp.in_replay()
    assert not dp.in_replay()
    # the single-device path stays attribution-free even mid-replay
    with dp.replay_scope():
        assert dp.span_attrs(None) == {}


def test_device_spans_after_evict_keep_original_and_split_replay():
    """After DevicePool.evict, the dead chip's pre-eviction spans stay
    under its original key and the survivor's replayed windows land
    under ``<survivor>:replay`` — the snapshot can no longer conflate
    replayed work with the survivor's own."""
    pool = dp.make_pool(2)
    tr = tele.Tracer(recording=True)
    d0, d1 = pool.devices
    k0, k1 = dp._attr_id(d0), dp._attr_id(d1)
    # organic work on both chips
    with tr.span(tele.SPAN_APPLY_DISPATCH, window=0, **dp.span_attrs(d0)):
        pass
    with tr.span(tele.SPAN_APPLY_DISPATCH, window=1, **dp.span_attrs(d1)):
        pass
    # chip 1 dies; window 1 replays on chip 0 the way streamed.py does:
    # umbrella span attributed to the FAILED chip, nested dispatch
    # inside a replay_scope on the survivor
    assert pool.evict(d1, reason="test", tracer=tr)
    with tr.span(tele.SPAN_POOL_REPLAY, window=1, **dp.span_attrs(d1)), \
            dp.replay_scope():
        with tr.span(tele.SPAN_APPLY_DISPATCH, window=1,
                     **dp.span_attrs(d0)):
            pass
    snap = tr.snapshot()
    disp = snap["device_spans"][tele.SPAN_APPLY_DISPATCH]
    assert disp[str(k0)]["count"] == 1       # organic only
    assert disp[str(k1)]["count"] == 1       # pre-eviction, original key
    assert disp[f"{k0}:replay"]["count"] == 1  # the replayed window
    # the umbrella names the failed chip, eviction counted
    assert snap["device_spans"][tele.SPAN_POOL_REPLAY][str(k1)]["count"] == 1
    assert snap["counters"][tele.C_DEVICE_EVICTED] == 1
    assert pool.alive_devices() == [d0]
