"""Device health scoreboard, hedged dispatch, and the SDC audit
(docs/ROBUSTNESS.md "Device health, hedging, and SDC audit").

The quiet-failure matrix: a straggler chip must get hedged around
(byte-identically), a bit-flipping chip must get caught by the audit
and quarantined with the published output still byte-identical to a
clean run, and the scoreboard's state machine (healthy -> suspect ->
probation -> evicted, with the cooldown + known-answer re-admission
probe) must drive placement without ever changing output bytes.
"""

import hashlib
import os
import sys
import threading
import time

import numpy as np
import pytest

from adam_tpu.parallel import device_pool as dp
from adam_tpu.utils import faults
from adam_tpu.utils import health as health_mod
from adam_tpu.utils import retry as retry_mod
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


@pytest.fixture(autouse=True)
def _clean_state():
    """Every test starts and ends with a fresh board, disarmed faults,
    fast backoff, and the global tracer untouched."""
    os.environ["ADAM_TPU_RETRY_BACKOFF_S"] = "0.001"
    health_mod.reset_board()
    was_recording = tele.TRACE.recording
    yield
    faults.clear()
    health_mod.reset_board()
    retry_mod.clear_cancel_event()
    for k in ("ADAM_TPU_RETRY_BACKOFF_S", "ADAM_TPU_HEDGE_FACTOR",
              "ADAM_TPU_AUDIT_RATE", "ADAM_TPU_AUDIT_SEED",
              "ADAM_TPU_HEDGE_MIN_S", "ADAM_TPU_HEDGE_MIN_SAMPLES"):
        os.environ.pop(k, None)
    tele.TRACE.recording = was_recording


# ---------------------------------------------------------------------------
# Scoreboard state machine (fake clock)
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _board(**kw):
    clock = _Clock()
    b = health_mod.HealthBoard(
        clock=clock, suspect_score=3.0, probation_score=6.0,
        decay_halflife_s=30.0, cooldown_s=10.0, latency_factor=4.0,
        **kw,
    )
    return b, clock


def test_scoreboard_demotion_and_decay():
    b, clock = _board()
    tr = tele.Tracer(recording=True)
    assert b.state("cpu:0") == health_mod.HEALTHY
    for _ in range(5):
        b.note_retry("cpu:0", tracer=tr)      # 5 x 0.5 = 2.5 < 3
    assert b.state("cpu:0") == health_mod.HEALTHY
    b.note_retry("cpu:0", tracer=tr)          # 3.0 -> suspect
    assert b.state("cpu:0") == health_mod.SUSPECT
    assert not b.blocked("cpu:0")             # suspect still places
    snap = tr.snapshot()
    assert snap["counters"][tele.C_HEALTH_DEMOTED] == 1
    assert snap["health"]["cpu:0"]["state"] == health_mod.SUSPECT
    # decay walks a suspect back to healthy (half-life 30s)
    clock.t += 120.0
    assert b.state("cpu:0") == health_mod.HEALTHY


def test_scoreboard_probation_excludes_and_probe_readmits():
    b, clock = _board()
    tr = tele.Tracer(recording=True)
    for _ in range(4):
        b.note_timeout("cpu:1", tracer=tr)    # 4 x 1.5 = 6 -> probation
    assert b.state("cpu:1") == health_mod.PROBATION
    assert b.blocked("cpu:1")
    assert tr.snapshot()["counters"][tele.C_HEALTH_PROBATION] == 1
    # cooldown not elapsed: nothing due
    assert b.due_probes() == []
    clock.t += 10.0
    assert b.due_probes() == ["cpu:1"]
    # cooldown restarted: a failing probe can't hot-loop
    assert b.due_probes() == []
    b.readmit("cpu:1", tracer=tr)
    assert b.state("cpu:1") == health_mod.HEALTHY
    assert not b.blocked("cpu:1")
    assert tr.snapshot()["counters"][tele.C_HEALTH_READMITTED] == 1


def test_scoreboard_quarantine_and_probe_failure():
    b, clock = _board()
    tr = tele.Tracer(recording=True)
    b.quarantine("cpu:2", reason="sdc audit mismatch", tracer=tr)
    assert b.state("cpu:2") == health_mod.PROBATION
    assert b.blocked("cpu:2")
    clock.t += 10.0
    assert b.due_probes() == ["cpu:2"]
    b.probe_failed("cpu:2", tracer=tr)
    assert b.state("cpu:2") == health_mod.EVICTED
    assert b.blocked("cpu:2")
    # evicted is terminal: no more probes ever
    clock.t += 100.0
    assert b.due_probes() == []
    snap = tr.snapshot()
    assert snap["counters"][tele.C_HEALTH_PROBE_FAILED] == 1
    assert snap["health"]["cpu:2"]["state"] == health_mod.EVICTED


def test_latency_breach_penalizes_straggler_only():
    b, _clock = _board()
    tr = tele.Tracer(recording=True)
    # build the pooled histogram: 20 normal walls across two devices
    for i in range(20):
        b.observe_latency("bqsr.apply", f"cpu:{i % 2}", 0.01, tracer=tr)
    assert b.state("cpu:0") == health_mod.HEALTHY
    # one chip starts stretching every window to 100 x the pool
    for _ in range(6):
        b.observe_latency("bqsr.apply", "cpu:1", 1.0, tracer=tr)
    assert b.state("cpu:1") == health_mod.PROBATION
    assert b.state("cpu:0") == health_mod.HEALTHY
    # the breached walls stayed OUT of the pooled histogram, so the
    # hedge threshold still reflects the healthy tail
    os.environ["ADAM_TPU_HEDGE_FACTOR"] = "3"
    thr = b.hedge_threshold("bqsr.apply")
    assert thr is not None and thr < 0.5


def test_single_blip_charges_once_not_its_decay_tail():
    """One transient stall (GC pause, network hiccup) must cost ONE
    latency penalty — not one per healthy window while the EWMA's
    decay tail stays above the bound — or a single blip walks a
    healthy chip to probation."""
    b, _clock = _board()
    tr = tele.Tracer(recording=True)
    for _ in range(20):
        b.observe_latency("bqsr.apply", "cpu:0", 0.01, tracer=tr)
    b.observe_latency("bqsr.apply", "cpu:1", 1.0, tracer=tr)  # the blip
    for _ in range(10):  # healthy again, but the EWMA decays slowly
        b.observe_latency("bqsr.apply", "cpu:1", 0.01, tracer=tr)
    assert b.status()["cpu:1"]["signals"]["latency"] == 1
    assert b.state("cpu:1") == health_mod.HEALTHY


def test_cold_start_straggler_caught_by_peer_comparison():
    """A chip slow from its FIRST window contaminates the pooled p99
    it is judged against (half the warmup samples on a 2-device pool),
    so the pooled bound alone would never flag it — the cross-device
    peer-EWMA check must."""
    b, _clock = _board()
    tr = tele.Tracer(recording=True)
    for _ in range(10):
        b.observe_latency("bqsr.apply", "cpu:0", 0.01, tracer=tr)
        b.observe_latency("bqsr.apply", "cpu:1", 0.1, tracer=tr)
    assert b.state("cpu:1") == health_mod.PROBATION
    assert b.state("cpu:0") == health_mod.HEALTHY
    assert "peer" in b.status()["cpu:1"]["reason"]


def test_due_probes_candidates_preserve_foreign_dueness():
    """A pool claims (and restarts the cooldown of) only devices it
    can actually probe: another pool's due device stays due for the
    pool that CAN reach it."""
    b, clock = _board()
    b.quarantine("cpu:9")
    clock.t += 10.0
    assert b.due_probes(candidates=["cpu:0"]) == []  # not claimed
    assert b.due_probes(candidates=["cpu:9"]) == ["cpu:9"]
    assert b.due_probes(candidates=["cpu:9"]) == []  # cooldown restarted


def test_hedge_loss_walks_straggler_to_probation():
    """A chip so slow that EVERY window hedges produces no completed
    wall for observe_latency — the lost races themselves must feed the
    scoreboard, or the straggler hides behind the rescue forever."""
    b, _clock = _board()
    tr = tele.Tracer(recording=True)
    for _ in range(5):
        b.note_hedge_lost("cpu:1", "bqsr.apply", tracer=tr)  # 5 x 1.0
    assert b.state("cpu:1") == health_mod.SUSPECT
    b.note_hedge_lost("cpu:1", "bqsr.apply", tracer=tr)      # 6 x 1.0
    assert b.state("cpu:1") == health_mod.PROBATION
    assert b.blocked("cpu:1")
    row = b.status()["cpu:1"]
    assert row["signals"]["latency"] == 6
    assert "hedge" in row["reason"]


def test_hedge_threshold_gating():
    b, _clock = _board()
    assert b.hedge_threshold("bqsr.apply") is None  # factor unset
    os.environ["ADAM_TPU_HEDGE_FACTOR"] = "2"
    assert b.hedge_threshold("bqsr.apply") is None  # no samples
    for _ in range(health_mod.MIN_LATENCY_SAMPLES):
        b.observe_latency("bqsr.apply", "cpu:0", 0.2)
    thr = b.hedge_threshold("bqsr.apply")
    assert thr is not None and thr >= 0.2  # ~2 x p99, floored
    # the floor keeps micro-walls from hedging every window
    b2, _ = _board()
    for _ in range(health_mod.MIN_LATENCY_SAMPLES):
        b2.observe_latency("k", "cpu:0", 1e-6)
    assert b2.hedge_threshold("k") >= 0.05


def test_audit_due_is_deterministic_and_rate_shaped():
    assert not health_mod.audit_due(5, rate=0.0)
    assert health_mod.audit_due(5, rate=1.0)
    picked = [w for w in range(400)
              if health_mod.audit_due(w, rate=0.25, seed=3)]
    again = [w for w in range(400)
             if health_mod.audit_due(w, rate=0.25, seed=3)]
    assert picked == again                      # pure function
    assert 60 <= len(picked) <= 140             # ~0.25 of 400
    other = [w for w in range(400)
             if health_mod.audit_due(w, rate=0.25, seed=4)]
    assert picked != other                      # seed moves the sample


def test_known_answer_probe_passes_on_real_device():
    import jax

    assert health_mod.probe_known_answer(jax.local_devices()[0])


# ---------------------------------------------------------------------------
# Pool integration: placement filtering, availability fallback, probes
# ---------------------------------------------------------------------------
def test_pool_placement_skips_probation_devices():
    pool = dp.DevicePool(limit=4)
    key1 = dp._device_key(pool.devices[1])
    pool.health.quarantine(key1)
    alive = pool.alive_devices()
    assert pool.devices[1] not in alive and len(alive) == 3
    # survivors() (the prewarm set) still includes the probation chip
    assert pool.devices[1] in pool.survivors()
    # placement round-robins over the healthy subset only
    seen = {dp._device_key(pool.device(i)) for i in range(8)}
    assert key1 not in seen


def test_pool_availability_beats_health():
    pool = dp.DevicePool(limit=2)
    for d in pool.devices:
        pool.health.quarantine(dp._device_key(d))
    # every survivor blocked -> the filter yields, placement continues
    assert pool.alive_devices() == pool.survivors()
    assert pool.device(0) is not None


def test_pool_probe_readmits_and_evicts(monkeypatch):
    pool = dp.DevicePool(limit=2)
    b = pool.health
    b.cooldown_s = 0.0
    key0 = dp._device_key(pool.devices[0])
    b.quarantine(key0)
    monkeypatch.setattr(health_mod, "probe_known_answer", lambda d: True)
    pool.device(0)  # placement runs the due probe
    assert b.state(key0) == health_mod.HEALTHY
    # now a probe that fails: probation -> evicted through pool.evict
    b.quarantine(key0)
    monkeypatch.setattr(health_mod, "probe_known_answer", lambda d: False)
    pool.device(0)
    assert b.state(key0) == health_mod.EVICTED
    assert pool.devices[0] not in pool.survivors()


def test_mesh_healthy_subset():
    from adam_tpu.parallel.partitioner import healthy_subset

    b, _clock = _board()
    devs = ["cpu:0", "cpu:1", "cpu:2"]
    assert healthy_subset(devs, b) == devs
    b.quarantine("cpu:1")
    assert healthy_subset(devs, b) == ["cpu:0", "cpu:2"]
    b.quarantine("cpu:0")
    b.quarantine("cpu:2")
    assert healthy_subset(devs, b) == devs  # availability fallback


# ---------------------------------------------------------------------------
# hedged_call unit matrix
# ---------------------------------------------------------------------------
def test_hedged_call_primary_fast_path():
    tr = tele.Tracer(recording=True)
    out, winner, fired = dp.hedged_call(
        lambda: "primary", lambda: "hedge", 5.0, tracer=tr
    )
    assert (out, winner, fired) == ("primary", "primary", False)
    assert tele.C_HEDGE_FIRED not in tr.snapshot()["counters"]


def test_hedged_call_hedge_wins_and_counters_reconcile():
    tr = tele.Tracer(recording=True)
    release = threading.Event()

    def slow_primary():
        release.wait(5.0)
        return "primary"

    out, winner, fired = dp.hedged_call(
        slow_primary, lambda: "hedge", 0.05, tracer=tr
    )
    release.set()
    assert (out, winner, fired) == ("hedge", "hedge", True)
    c = tr.snapshot()["counters"]
    assert c[tele.C_HEDGE_FIRED] == 1 and c[tele.C_HEDGE_WON] == 1
    assert c.get(tele.C_HEDGE_WASTED, 0) == 0
    assert c[tele.C_HEDGE_FIRED] == (
        c[tele.C_HEDGE_WON] + c.get(tele.C_HEDGE_WASTED, 0)
    )


def test_hedged_call_primary_beats_slow_hedge():
    tr = tele.Tracer(recording=True)

    def primary():
        time.sleep(0.1)
        return "primary"

    def hedge():
        time.sleep(0.5)
        return "hedge"

    out, winner, fired = dp.hedged_call(primary, hedge, 0.02, tracer=tr)
    assert (out, winner, fired) == ("primary", "primary", True)
    c = tr.snapshot()["counters"]
    assert c[tele.C_HEDGE_FIRED] == 1
    assert c[tele.C_HEDGE_WASTED] == 1
    assert c.get(tele.C_HEDGE_WON, 0) == 0


def test_hedged_call_hedge_failure_falls_back_to_primary():
    tr = tele.Tracer(recording=True)

    def primary():
        time.sleep(0.1)
        return "primary"

    def bad_hedge():
        raise RuntimeError("no alternate device")

    out, winner, fired = dp.hedged_call(primary, bad_hedge, 0.02,
                                        tracer=tr)
    assert (out, winner, fired) == ("primary", "primary", True)


def test_hedged_call_primary_error_propagates():
    def primary():
        raise ValueError("chip error")

    with pytest.raises(ValueError, match="chip error"):
        dp.hedged_call(primary, lambda: "hedge", 5.0,
                       tracer=tele.Tracer(recording=True))


# ---------------------------------------------------------------------------
# corrupt action + pass= selector (the fault grammar's data channel)
# ---------------------------------------------------------------------------
def test_corrupt_grammar_validation():
    (c,) = faults.parse_spec("device.fetch=corrupt,every=3,seed=9")
    assert c.action == "corrupt" and c.every == 3 and c.seed == 9
    with pytest.raises(ValueError):
        faults.parse_spec("device.dispatch=corrupt")  # not corrupt-capable
    with pytest.raises(ValueError):
        faults.parse_spec("parquet.write=corrupt")
    (c2,) = faults.parse_spec("device.fetch=delay:1,pass=apply")
    assert c2.pass_name == "apply"


def test_corrupt_array_flips_one_bit_deterministically():
    faults.install("device.fetch=corrupt,every=1,seed=5,times=1")
    a = np.arange(64, dtype=np.uint8)
    out = faults.corrupt_array("device.fetch", a)
    assert out is not a
    diff = np.bitwise_xor(out, a)
    assert diff.sum() > 0
    # exactly one bit flipped
    assert sum(bin(int(v)).count("1") for v in diff) == 1
    # times=1 spent: the next arrival passes through untouched
    out2 = faults.corrupt_array("device.fetch", a)
    assert out2 is a
    # same seed reproduces the same flip
    faults.install("device.fetch=corrupt,every=1,seed=5,times=1")
    again = faults.corrupt_array("device.fetch",
                                 np.arange(64, dtype=np.uint8))
    assert np.array_equal(again, out)


def test_corrupt_array_never_raises_on_scalar_results():
    """The data channel's contract: corrupt never raises — a 0-d fetch
    result (a scalar) flips a bit instead of blowing up the fetch with
    a view-cast ValueError."""
    faults.install("device.fetch=corrupt,every=1")
    a = np.int64(7) + np.zeros((), np.int64)  # 0-d array
    out = faults.corrupt_array("device.fetch", a)
    assert out.shape == () and int(out) != 7
    # object arrays pass through silently (nothing to flip)
    obj = np.array([object()])
    assert faults.corrupt_array("device.fetch", obj) is obj


def test_corrupt_ignores_point_channel_and_honors_pass():
    faults.install("device.fetch=corrupt,every=1")
    # the exception channel never fires corrupt clauses
    faults.point("device.fetch")  # must not raise or count the arrival
    a = np.zeros(8, np.int64)
    with tele.pass_scope("a"):
        same = faults.corrupt_array(
            "device.fetch", a, pass_name="a"
        )
    faults.install("device.fetch=corrupt,every=1,pass=apply")
    untouched = faults.corrupt_array("device.fetch", a, pass_name="a")
    assert untouched is a                     # wrong pass: no arrival
    flipped = faults.corrupt_array("device.fetch", a, pass_name="apply")
    assert not np.array_equal(flipped, a)
    assert same is not None


def test_device_fetch_routes_through_corrupt(monkeypatch):
    """A corrupt clause at device.fetch flips bits in a REAL fetched
    device array — the injection the audit must catch."""
    import jax

    x = jax.device_put(np.arange(256, dtype=np.uint8),
                       jax.local_devices()[0])
    from adam_tpu.utils.transfer import device_fetch

    faults.install("device.fetch=corrupt,every=1,times=1")
    got = device_fetch(x)
    clean = np.arange(256, dtype=np.uint8)
    assert not np.array_equal(got, clean)
    faults.clear()
    assert np.array_equal(device_fetch(x), clean)


# ---------------------------------------------------------------------------
# Drain-aware retry backoff (satellite)
# ---------------------------------------------------------------------------
def test_retry_backoff_sleep_is_drain_aware():
    ev = threading.Event()
    calls = []

    def failing():
        calls.append(1)
        raise faults.TransientFault("flaky")

    policy = retry_mod.RetryPolicy(attempts=5, backoff_s=30.0,
                                   max_backoff_s=30.0)
    t0 = time.monotonic()
    threading.Timer(0.2, ev.set).start()
    with pytest.raises(faults.TransientFault):
        retry_mod.retry_call(failing, site="t", policy=policy, cancel=ev)
    took = time.monotonic() - t0
    # the drain interrupted the 30s backoffs almost immediately, but
    # the attempt budget still ran out back to back — failure
    # semantics are untouched (a one-off transient mid-drain would
    # still absorb instead of surfacing as a spurious device failure)
    assert took < 5.0
    assert len(calls) == 5


def test_retry_cancel_event_registration_scoping():
    ev1, ev2 = threading.Event(), threading.Event()
    retry_mod.set_cancel_event(ev1)
    assert retry_mod.cancel_event() is ev1
    retry_mod.set_cancel_event(ev2)
    # clearing with the OLD event must not remove the new registration
    retry_mod.clear_cancel_event(ev1)
    assert retry_mod.cancel_event() is ev2
    retry_mod.clear_cancel_event(ev2)
    assert retry_mod.cancel_event() is None


def test_retry_uses_installed_event_when_set():
    ev = threading.Event()
    ev.set()
    retry_mod.set_cancel_event(ev)

    def failing():
        raise faults.TransientFault("flaky")

    policy = retry_mod.RetryPolicy(attempts=5, backoff_s=30.0)
    t0 = time.monotonic()
    with pytest.raises(faults.TransientFault):
        retry_mod.retry_call(failing, site="t", policy=policy)
    assert time.monotonic() - t0 < 2.0


# ---------------------------------------------------------------------------
# Mid-run quota throttle (satellite)
# ---------------------------------------------------------------------------
def test_quota_throttle_defers_then_grants():
    from adam_tpu.serve.quota import QuotaManager

    clock = _Clock()
    tr = tele.Tracer(recording=True)
    qm = QuotaManager("t1:bytes=1000", window_s=60.0, clock=clock,
                      tracer=tr)
    # under budget: zero-cost fast path, no deferral counted
    assert qm.throttle("t1", sleep=lambda s: None) == 0.0
    qm.charge("t1", nbytes=2000)
    slept = []

    def fake_sleep(s):
        slept.append(s)
        clock.t += 10.0  # each poll advances the fake clock

    deferred = qm.throttle("t1", sleep=fake_sleep, tracer=tr)
    # the charge aged out of the 60s window after ~6 polls
    assert deferred >= 60.0 and slept
    assert qm.check("t1") is None  # the grant can proceed now
    assert tr.snapshot()["counters"][tele.C_QUOTA_DEFERRED] == 1


def test_quota_throttle_stops_on_drain_and_bound():
    from adam_tpu.serve.quota import QuotaManager

    clock = _Clock()
    qm = QuotaManager("t1:bytes=10", window_s=1000.0, clock=clock)
    qm.charge("t1", nbytes=100)
    # should_stop wins immediately
    assert qm.throttle(
        "t1", should_stop=lambda: True, sleep=lambda s: None
    ) == 0.0
    # the bound caps a stuck budget
    def fake_sleep(s):
        clock.t += 5.0

    deferred = qm.throttle("t1", max_wait_s=20.0, sleep=fake_sleep)
    assert 20.0 <= deferred <= 30.0
    assert qm.check("t1") is not None  # still over budget: bounded, not stuck


def test_scheduler_pacer_defers_over_budget_tenant(tmp_path):
    """The pacer seam defers an over-budget tenant's grant and counts
    sched.quota.deferred — the unit twin of the serve-level smoke."""
    from adam_tpu.serve.job import JobSpec
    from adam_tpu.serve.quota import QuotaManager, THROTTLE_POLL_S
    from adam_tpu.serve.scheduler import JobScheduler

    sched = JobScheduler(str(tmp_path / "root"), max_jobs=1,
                         quota=QuotaManager("tA:bytes=100",
                                            window_s=0.4))
    try:
        was = tele.TRACE.recording
        tele.TRACE.recording = True
        spec = JobSpec(job_id="j1", tenant="tA", input="x", output="y")
        sched._interleaver.register("j1", tenant="tA")
        pace = sched._job_pacer(spec)
        sched.quota.charge("tA", nbytes=1000)  # blow the budget
        t0 = time.monotonic()
        pace("pass_a", 0, 50)  # must defer until the window expires
        took = time.monotonic() - t0
        assert took >= 0.2
        _c, _ = tele.TRACE.counters_and_gauges()
        assert _c.get(tele.C_QUOTA_DEFERRED, 0) >= 1
        # next grant is in budget again: fast path
        t0 = time.monotonic()
        pace("pass_a", 1, 10)
        assert time.monotonic() - t0 < 0.2
    finally:
        tele.TRACE.recording = was
        sched.close()


# ---------------------------------------------------------------------------
# End-to-end: straggler hedge + SDC audit on the real streamed pipeline
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def wgs_input(tmp_path_factory):
    from make_wgs_sam import make_wgs

    d = tmp_path_factory.mktemp("health")
    path = str(d / "in.sam")
    make_wgs(path, 2048, 100, n_contigs=2, contig_len=30_000,
             indel_every=800, snp_every=400)
    return d, path


def _parts_hash(out_dir):
    out = {}
    for f in sorted(os.listdir(out_dir)):
        if f.startswith("part-") and f.endswith(".parquet"):
            with open(os.path.join(out_dir, f), "rb") as fh:
                out[f] = hashlib.sha256(fh.read()).hexdigest()
    assert out
    return out


@pytest.fixture(scope="module")
def clean_baseline(wgs_input):
    from adam_tpu.pipelines.streamed import transform_streamed

    d, path = wgs_input
    out = str(d / "clean1.adam")
    os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
    try:
        transform_streamed(path, out, window_reads=256, devices=1)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    return _parts_hash(out)


def _run(path, out, spec, devices, env=None):
    from adam_tpu.pipelines.streamed import transform_streamed

    os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
    os.environ.update(env or {})
    was = tele.TRACE.recording
    tele.TRACE.recording = True
    tele.TRACE.reset()
    faults.install(spec)
    try:
        stats = transform_streamed(path, out, window_reads=256,
                                   devices=devices)
        board = health_mod.BOARD.status()
    finally:
        faults.clear()
        snap = tele.TRACE.snapshot()
        tele.TRACE.recording = was
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
        for k in env or {}:
            os.environ.pop(k, None)
    return stats, snap, board


def test_streamed_sdc_audit_catches_corrupt_and_quarantines(
    wgs_input, clean_baseline
):
    """ISSUE acceptance: a seeded ``corrupt`` injection at
    ``device.fetch`` is caught by the audit (device.audit.mismatch >
    0), the offending device quarantines, and the published output is
    byte-identical to a fault-free run."""
    d, path = wgs_input
    out = str(d / "sdc2.adam")
    stats, snap, board = _run(
        path, out,
        "device.fetch=corrupt,pass=apply,every=3,seed=11",
        devices=2,
        env={"ADAM_TPU_AUDIT_RATE": "1.0"},
    )
    c = snap["counters"]
    assert c.get(tele.C_FAULT_INJECTED, 0) >= 1
    assert c.get(tele.C_AUDIT_SAMPLED, 0) >= stats["windows_fresh"]
    assert c.get(tele.C_AUDIT_MISMATCH, 0) >= 1
    # every flip was caught: no corrupt byte survived to disk
    assert _parts_hash(out) == clean_baseline
    # the producing chip went through probation (quarantine)
    assert c.get(tele.C_HEALTH_PROBATION, 0) >= 1
    assert any(
        row["state"] in (health_mod.PROBATION, health_mod.EVICTED)
        for row in board.values()
    )
    # the health section rode into the snapshot for the analyzer
    assert snap["health"]


def test_streamed_audit_clean_run_no_mismatch(wgs_input, clean_baseline):
    """Audit on, no corruption: every sampled window verifies, nothing
    quarantines, output identical — the audit itself never perturbs
    the published bytes."""
    d, path = wgs_input
    out = str(d / "audit_clean.adam")
    stats, snap, board = _run(
        path, out, None, devices=2,
        env={"ADAM_TPU_AUDIT_RATE": "0.5", "ADAM_TPU_AUDIT_SEED": "7"},
    )
    c = snap["counters"]
    assert c.get(tele.C_AUDIT_SAMPLED, 0) >= 1
    assert c.get(tele.C_AUDIT_MISMATCH, 0) == 0
    assert c.get(tele.C_HEALTH_PROBATION, 0) == 0
    assert _parts_hash(out) == clean_baseline


def test_streamed_hedge_rescues_straggler_byte_identically(
    wgs_input, clean_baseline
):
    """ISSUE acceptance: an injected straggler (seeded delay on one
    device's pass-C fetches) makes the hedge fire; the winner's bytes
    match the un-hedged run bit-for-bit and the hedge counters
    reconcile (fired == won + wasted)."""
    d, path = wgs_input
    out = str(d / "hedge2.adam")
    stats, snap, board = _run(
        path, out,
        # stall device 1's apply-pass fetches only once the latency
        # pool is warm (>= ADAM_TPU_HEDGE_MIN_SAMPLES pooled walls —
        # the hedge threshold needs a p99 first): each stalled fetch
        # then exceeds factor x p99 and the hedge re-runs the window
        # on device 0.  after=6 skips the first ~3 of device 1's
        # windows (the packed finish fetches ~2 payload slices per
        # window), past the 4-sample floor on this 8-window run.
        "device.fetch=delay:1.0,device=1,pass=apply,after=6",
        devices=2,
        env={
            "ADAM_TPU_HEDGE_FACTOR": "3",
            "ADAM_TPU_HEDGE_MIN_S": "0.05",
            "ADAM_TPU_HEDGE_MIN_SAMPLES": "4",
        },
    )
    c = snap["counters"]
    assert c.get(tele.C_HEDGE_FIRED, 0) >= 1, c
    assert c.get(tele.C_HEDGE_WON, 0) >= 1, c
    assert c[tele.C_HEDGE_FIRED] == (
        c.get(tele.C_HEDGE_WON, 0) + c.get(tele.C_HEDGE_WASTED, 0)
    )
    assert _parts_hash(out) == clean_baseline


def test_heartbeat_carries_device_health_field():
    tr = tele.Tracer(recording=True)
    hb = tele.Heartbeat([tr], sink="stderr", interval_s=60.0)
    line = hb.sample()
    assert tuple(line.keys()) == tele.HEARTBEAT_FIELDS
    assert line["schema"] == "adam_tpu.heartbeat/7"
    assert line["device_health"] is None  # nothing tracked yet
    health_mod.BOARD.quarantine("cpu:3")
    line2 = hb.sample()
    assert line2["device_health"]["cpu:3"] == health_mod.PROBATION


def test_analyzer_renders_device_health_section():
    from adam_tpu.utils.analyzer import analyze, render_report

    tr = tele.Tracer(recording=True)
    tr.record_health("cpu:1", health_mod.PROBATION, 6.0,
                     "sdc audit mismatch on window 3")
    tr.count(tele.C_AUDIT_SAMPLED, 10)
    tr.count(tele.C_AUDIT_MISMATCH, 2)
    tr.count(tele.C_HEDGE_FIRED, 3)
    tr.count(tele.C_HEDGE_WON, 2)
    tr.count(tele.C_HEDGE_WASTED, 1)
    tr.count(tele.C_HEALTH_PROBATION, 1)
    report = analyze(tr.snapshot())
    h = report["health"]
    assert h["devices"]["cpu:1"]["state"] == health_mod.PROBATION
    assert h["audit_mismatch"] == 2 and h["hedge_fired"] == 3
    text = render_report(report)
    assert "Device health" in text
    assert "cpu:1: probation" in text
    assert "3 fired" in text and "2 mismatch(es)" in text
    assert "silent data corruption" in text
