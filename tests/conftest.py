"""Test configuration.

Tests run on CPU with 8 virtual devices so the multi-chip sharding paths
(mesh, shard_map, collectives) are exercised without TPU hardware — the
analog of the reference's Spark `local[N]` test harness
(ADAMFunSuite / SparkFunSuite in the reference test tree).

Env vars must be set before the first `import jax` anywhere.
"""

import os
import sys
import pathlib

os.environ["JAX_PLATFORMS"] = "cpu"
# don't share the persistent compile cache with tunneled-backend runs:
# its "cpu" entries may be AOT results for a different machine
os.environ.setdefault("ADAM_TPU_NO_COMPILE_CACHE", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# The axon sitecustomize (TPU tunnel) registers an 'axon' PJRT plugin in
# every interpreter; its client init dials the tunnel even under
# JAX_PLATFORMS=cpu and can hang when the single-chip lease is busy.
# Tests are CPU-only by design — drop the plugin before any backend init.
try:  # pragma: no cover - environment-specific
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # the sitecustomize imports jax at interpreter start, capturing
    # JAX_PLATFORMS=axon from the ambient env before this file runs
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402

# Golden files / fixtures from the reference tree (read-only differential
# test inputs; tests that need them skip when the tree is absent).
REFERENCE_RESOURCES = pathlib.Path("/root/reference/adam-core/src/test/resources")


@pytest.fixture(scope="session")
def ref_resources():
    if not REFERENCE_RESOURCES.is_dir():
        pytest.skip("reference test resources not available")
    return REFERENCE_RESOURCES
