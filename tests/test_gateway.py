"""HTTP gateway (adam_tpu/gateway; docs/SERVING.md): wire-protocol
units and fuzz, idempotency-keyed submission (across gateway restarts
too), typed 429/503 back-pressure honored by the client policy,
cursor-resumable event streaming, Range-resumable sha256-verified part
fetch, and the two-client/two-tenant end-to-end run byte-compared to
solo runs.

Most tests ride a stub transform (timing-free); the end-to-end and
SIGTERM tests drive the REAL streamed pipeline on the numpy backend
over real sockets — the gateway's core contract is that the wire
changes how work is asked for, never the bytes."""

import hashlib
import json
import http.client
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from adam_tpu.api.transform_service import TransformService
from adam_tpu.gateway import protocol
from adam_tpu.gateway.client import (
    GatewayBusy,
    GatewayClient,
    GatewayError,
    resolve_url,
)
from adam_tpu.gateway.server import GatewayServer
from adam_tpu.serve import scheduler as sched_mod
from adam_tpu.serve.job import JobSpec
from adam_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HB = "adam_tpu.heartbeat/7"


def _parts_hash(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(d)) if f.startswith("part-")
    }


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# Protocol units
# ---------------------------------------------------------------------------
def test_parse_listen():
    assert protocol.parse_listen("127.0.0.1:0") == ("127.0.0.1", 0)
    assert protocol.parse_listen("0.0.0.0:8765") == ("0.0.0.0", 8765)
    for bad in ("", "8765", "host:", "host:x", "host:70000"):
        with pytest.raises(ValueError):
            protocol.parse_listen(bad)


def test_parse_range():
    assert protocol.parse_range(None, 100) is None
    assert protocol.parse_range("bytes=0-", 100) == (0, 99)
    assert protocol.parse_range("bytes=10-19", 100) == (10, 19)
    assert protocol.parse_range("bytes=90-500", 100) == (90, 99)
    assert protocol.parse_range("bytes=-25", 100) == (75, 99)
    for bad in ("bytes=100-", "bytes=5-2", "bytes=-0", "bytes=",
                "octets=1-2", "bytes=1-2,5-6"):
        with pytest.raises(protocol.RangeError):
            protocol.parse_range(bad, 100)


def test_retry_after_from_grants():
    # cold service: conservative default
    assert protocol.retry_after_s([]) == 2
    assert protocol.retry_after_s([5.0]) == 2
    # fast cadence (0.1 s/window): ~8 windows, floored at 1 s
    fast = [i * 0.1 for i in range(20)]
    assert protocol.retry_after_s(fast, now=2.0) == 1
    # slow cadence (2 s/window): 8 windows = 16 s
    slow = [i * 2.0 for i in range(20)]
    assert protocol.retry_after_s(slow, now=38.5) == 16
    # a stalled pool decays toward the cap instead of advertising its
    # last healthy cadence forever
    stalled = [i * 0.1 for i in range(3)]
    assert protocol.retry_after_s(stalled, now=1000.0) == \
        protocol.RETRY_AFTER_MAX_S
    lo, hi = protocol.RETRY_AFTER_MIN_S, protocol.RETRY_AFTER_MAX_S
    for times in (fast, slow, stalled):
        assert lo <= protocol.retry_after_s(times, now=50.0) <= hi


def test_part_name_ok():
    assert protocol.part_name_ok("part-r-00000.parquet")
    assert protocol.part_name_ok("part-realigned.parquet")
    for bad in ("", "x.parquet", "part-", "part-a/b", "part-..",
                "part-a..b", "_metadata", ".part-hidden"):
        assert not protocol.part_name_ok(bad), bad


# ---------------------------------------------------------------------------
# Stub-backed gateway fixture
# ---------------------------------------------------------------------------
@pytest.fixture()
def stub_transform(monkeypatch):
    """Gate-controlled streamed-pipeline stub (timing-free admission
    tests; the test_serve.py idiom)."""
    release = threading.Event()

    def fake(inp, out, **kw):
        assert release.wait(30), "stub never released"
        return {"n_reads": 0, "windows_fresh": 0}

    monkeypatch.setattr(sched_mod.streamed_mod, "transform_streamed",
                        fake)
    return {"release": release}


@pytest.fixture()
def gateway(tmp_path):
    """One service + gateway + typed client on a real socket."""
    svc = TransformService(str(tmp_path / "root"), max_jobs=1)
    gw = GatewayServer(svc)
    gw.start()
    client = GatewayClient(gw.url)
    yield {"svc": svc, "gw": gw, "client": client,
           "root": str(tmp_path / "root"), "tmp": tmp_path}
    gw.close()
    svc.close()


def _doc(tmp_path, jid, **kw):
    d = {"input": "in.sam", "output": str(tmp_path / f"{jid}.adam")}
    d.update(kw)
    return d


# ---------------------------------------------------------------------------
# Submission: idempotency key, conflict, duplicate-safe retries
# ---------------------------------------------------------------------------
def test_submit_idempotent_and_conflict(gateway, stub_transform):
    c = gateway["client"]
    tmp = gateway["tmp"]
    got = c.submit("j1", _doc(tmp, "j1"))
    # the reply also echoes the minted trace_id (docs/OBSERVABILITY.md)
    assert got.pop("trace_id")
    assert got == {"job_id": "j1", "state": "pending"}
    # identical re-PUT (a client retry whose first response was lost):
    # success, carrying the job's current state
    again = c.submit("j1", _doc(tmp, "j1"))
    assert again["duplicate"] is True
    assert again["state"] in ("pending", "running")
    # same id, different spec: 409, never a silent overwrite
    with pytest.raises(GatewayError) as ei:
        c.submit("j1", _doc(tmp, "j1", window_reads=1024))
    assert ei.value.status == 409 and ei.value.kind == "conflict"
    # body job_id contradicting the path is malformed
    with pytest.raises(GatewayError) as ei:
        c.submit("j1", dict(_doc(tmp, "j1"), job_id="other"))
    assert ei.value.status == 400
    stub_transform["release"].set()
    assert gateway["svc"].wait(timeout=30)
    done = c.submit("j1", _doc(tmp, "j1"))
    assert done["duplicate"] is True and done["state"] == "done"
    # gateway.requests / request.seconds accounted (the serve ctor
    # keeps the global tracer recording)
    from adam_tpu.utils import telemetry as tele

    snap = tele.TRACE.snapshot()
    assert snap["counters"].get(tele.C_GW_REQUESTS, 0) > 0
    assert snap["histograms"][tele.H_GW_REQUEST_SECONDS]["count"] > 0


def test_idempotent_resubmission_across_gateway_restart(
    tmp_path, stub_transform,
):
    root = str(tmp_path / "root")
    svc = TransformService(root, max_jobs=1)
    gw = GatewayServer(svc)
    gw.start()
    c = GatewayClient(gw.url)
    doc = _doc(tmp_path, "r1")
    assert c.submit("r1", doc)["state"] == "pending"
    stub_transform["release"].set()
    assert svc.wait(timeout=30)
    gw.close()
    svc.close()
    # the whole process "restarts": a fresh service recovers the
    # durable JOB.json records, a fresh gateway binds a fresh port —
    # and the client's blind re-PUT is still duplicate-safe
    svc2 = TransformService(root, max_jobs=1)
    svc2.recover()
    gw2 = GatewayServer(svc2)
    gw2.start()
    try:
        c2 = GatewayClient(gw2.url)
        again = c2.submit("r1", doc)
        assert again["duplicate"] is True and again["state"] == "done"
        with pytest.raises(GatewayError) as ei:
            c2.submit("r1", dict(doc, window_reads=2048))
        assert ei.value.status == 409
        # the discovery document tracks the NEW address
        assert resolve_url(root) == gw2.url
        assert GatewayClient(resolve_url(root)).status("r1")["state"] \
            == "done"
    finally:
        gw2.close()
        svc2.close()


# ---------------------------------------------------------------------------
# Typed back-pressure: 429/503 + Retry-After, honored by the client
# ---------------------------------------------------------------------------
def test_busy_429_503_and_client_policy(gateway, stub_transform):
    c = gateway["client"]
    tmp = gateway["tmp"]
    assert c.submit("b1", _doc(tmp, "b1"))["state"] == "pending"
    # slot taken (max_jobs=1): capacity -> 429 with Retry-After
    with pytest.raises(GatewayBusy) as ei:
        c.submit("b2", _doc(tmp, "b2"))
    assert ei.value.status == 429 and ei.value.kind == "capacity"
    assert ei.value.retry_after >= protocol.RETRY_AFTER_MIN_S
    # the retrying client sleeps >= the server hint and wins once the
    # slot frees (sleep recorded, not actually slept)
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        stub_transform["release"].set()  # free the slot mid-backoff
        gateway["svc"].wait(timeout=30)

    from adam_tpu.utils.retry import RetryPolicy

    got = c.submit_with_retry(
        "b2", _doc(tmp, "b2"),
        policy=RetryPolicy(attempts=3, backoff_s=0.01),
        sleep=fake_sleep,
    )
    assert got["state"] == "pending"
    assert sleeps and sleeps[0] >= ei.value.retry_after
    assert gateway["svc"].wait(timeout=30)
    # draining -> 503 (and the gateway's own stop_accepting answers
    # 503 even before the scheduler hears about the drain)
    gateway["gw"].stop_accepting()
    with pytest.raises(GatewayBusy) as ei:
        c.submit("b3", _doc(tmp, "b3"))
    assert ei.value.status == 503 and ei.value.kind == "draining"
    gateway["svc"].request_drain()
    with pytest.raises(GatewayBusy) as ei:
        c.submit("b4", _doc(tmp, "b4"))
    assert ei.value.status == 503
    from adam_tpu.utils import telemetry as tele

    assert tele.TRACE.snapshot()["counters"].get(tele.C_GW_BUSY, 0) >= 3


def test_gateway_accept_transient_maps_to_503(gateway, stub_transform):
    c = gateway["client"]
    faults.install("gateway.accept=transient,times=1")
    try:
        with pytest.raises(GatewayBusy) as ei:
            c.status()
        assert ei.value.status == 503
        assert ei.value.retry_after >= 1
        # one-shot clause: the next request sails through — exactly
        # what submit_with_retry's transport/busy handling rides
        assert "jobs" in c.status()
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# Wire fuzz: malformed manifests, bad routes, truncated bodies
# ---------------------------------------------------------------------------
def _raw(gateway):
    host, port = gateway["client"].host, gateway["client"].port
    return http.client.HTTPConnection(host, port, timeout=10)


def test_fuzz_bad_manifests_and_routes(gateway):
    c = gateway["client"]
    tmp = gateway["tmp"]
    # not JSON
    conn = _raw(gateway)
    conn.request("PUT", "/v1/jobs/f1", body=b"{not json",
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 400 and b"bad_manifest" in r.read()
    # unknown manifest field
    with pytest.raises(GatewayError) as ei:
        c.submit("f1", dict(_doc(tmp, "f1"), nope=1))
    assert ei.value.status == 400 and "nope" in str(ei.value)
    # manifest that parses but violates JobSpec validation
    with pytest.raises(GatewayError) as ei:
        c.submit("f1", dict(_doc(tmp, "f1"), weight=0))
    assert ei.value.status == 400
    # bad job id in the path
    conn.request("PUT", "/v1/jobs/..", body=b"{}",
                 headers={"Content-Length": "2"})
    assert conn.getresponse().status in (400, 404)
    # unknown routes
    for path in ("/", "/v2/jobs", "/v1/other", "/v1/jobs/f1/nope",
                 "/v1/jobs/f1/parts/a/b"):
        conn = _raw(gateway)
        conn.request("GET", path)
        assert conn.getresponse().status == 404, path
    # wrong method
    conn = _raw(gateway)
    conn.request("DELETE", "/v1/jobs")
    assert conn.getresponse().status == 405


def _recv_response(sock) -> bytes:
    """Read one full HTTP response off a raw socket (headers + the
    Content-Length'd body — a single recv can race the body's TCP
    segment)."""
    import re

    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(4096)
        if not chunk:
            return data
        data += chunk
    head, _, body = data.partition(b"\r\n\r\n")
    m = re.search(rb"[Cc]ontent-[Ll]ength: (\d+)", head)
    want = int(m.group(1)) if m else 0
    while len(body) < want:
        chunk = sock.recv(4096)
        if not chunk:
            break
        body += chunk
    return head + b"\r\n\r\n" + body


def test_fuzz_oversized_and_truncated_bodies(gateway):
    conn = _raw(gateway)
    # oversized Content-Length refused before the body is read
    conn.request("PUT", "/v1/jobs/big", headers={
        "Content-Length": str(protocol.MAX_MANIFEST_BYTES + 1),
    })
    r = conn.getresponse()
    assert r.status == 413
    doc = json.loads(r.read())
    assert doc["schema"] == protocol.ERROR_SCHEMA
    assert doc["kind"] == "too_large"
    # truncated chunked body: size line promises more than arrives
    sock = socket.create_connection(
        (gateway["client"].host, gateway["client"].port), timeout=10,
    )
    try:
        sock.sendall(
            b"PUT /v1/jobs/t1 HTTP/1.1\r\n"
            b"Host: x\r\nTransfer-Encoding: chunked\r\n\r\n"
            b"A\r\n{\"in"  # promises 10 bytes, sends 4, hangs up
        )
        sock.shutdown(socket.SHUT_WR)
        resp = _recv_response(sock)
        assert b"400" in resp.split(b"\r\n", 1)[0], resp
        assert b"truncated" in resp
    finally:
        sock.close()
    # chunked body with a garbage size line
    sock = socket.create_connection(
        (gateway["client"].host, gateway["client"].port), timeout=10,
    )
    try:
        sock.sendall(
            b"PUT /v1/jobs/t2 HTTP/1.1\r\n"
            b"Host: x\r\nTransfer-Encoding: chunked\r\n\r\n"
            b"ZZZ\r\nhello\r\n0\r\n\r\n"
        )
        resp = _recv_response(sock)
        assert b"400" in resp.split(b"\r\n", 1)[0], resp
    finally:
        sock.close()
    # a well-formed chunked manifest still parses (the happy twin)
    body = json.dumps({"input": "i", "output": "o"}).encode()
    sock = socket.create_connection(
        (gateway["client"].host, gateway["client"].port), timeout=10,
    )
    try:
        sock.sendall(
            b"PUT /v1/jobs/nope-dir HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            + f"{len(body):X}\r\n".encode() + body + b"\r\n0\r\n\r\n"
        )
        resp = sock.recv(4096)
        # admitted (201): chunked transfer is a first-class citizen
        assert b"201" in resp.split(b"\r\n", 1)[0], resp
    finally:
        sock.close()
        # the stub isn't armed here; the job fails and quarantines in
        # the background, which is fine — this test only cares that
        # the chunked body PARSED
        gateway["svc"].wait(timeout=60)


# ---------------------------------------------------------------------------
# Event streaming: line cursor, torn tails, resumability
# ---------------------------------------------------------------------------
def _hb_line(seq, done=False, ok=True):
    return json.dumps({"schema": HB, "seq": seq, "done": done,
                       "ok": ok}) + "\n"


def test_events_cursor_poll_and_resume(gateway, stub_transform):
    c = gateway["client"]
    tmp = gateway["tmp"]
    with pytest.raises(GatewayError) as ei:
        c.poll_events("ghost")
    assert ei.value.status == 404
    c.submit("e1", _doc(tmp, "e1"))
    hb = gateway["svc"].scheduler.heartbeat_path("e1")
    with open(hb, "w") as fh:
        fh.write(_hb_line(0) + _hb_line(1))
        fh.write('{"schema": "%s", "seq": 2, "done": false' % HB)  # torn
    cur, lines = c.poll_events("e1")
    assert [l["seq"] for l in lines] == [0, 1]  # torn tail never ships
    assert cur == 2
    # complete the torn line + append: resume from the cursor sees
    # exactly the new lines
    with open(hb, "a") as fh:
        fh.write(', "ok": true}\n' + _hb_line(3))
    cur2, lines2 = c.poll_events("e1", cursor=cur)
    assert [l["seq"] for l in lines2] == [2, 3]
    # cursor past a rotation (file now shorter): re-delivered from the
    # top instead of starving
    with open(hb, "w") as fh:
        fh.write(_hb_line(0))
    cur3, lines3 = c.poll_events("e1", cursor=cur2 + 2)
    assert [l["seq"] for l in lines3] == [0]
    # follow mode ends on done=true and survives reconnect-from-cursor
    with open(hb, "w") as fh:
        fh.write(_hb_line(0) + _hb_line(1))
    got = []

    def follow():
        for cur, line in c.events("e1", cursor=1):
            got.append((cur, line["seq"]))

    t = threading.Thread(target=follow, daemon=True)
    t.start()
    time.sleep(0.6)
    with open(hb, "a") as fh:
        fh.write(_hb_line(2, done=True))
    t.join(15)
    assert not t.is_alive()
    assert [seq for _, seq in got] == [1, 2]
    stub_transform["release"].set()
    gateway["svc"].wait(timeout=30)


# ---------------------------------------------------------------------------
# Part fetch: Range resume, sha verification, path containment
# ---------------------------------------------------------------------------
@pytest.fixture()
def fetch_job(gateway, stub_transform, tmp_path):
    """A done job whose output dir holds two synthetic parts."""
    out_dir = tmp_path / "fj.adam"
    out_dir.mkdir()
    parts = {
        "part-r-00000.parquet": os.urandom(200_000),
        "part-r-00001.parquet": os.urandom(64 * 1024),  # == chunk size
    }
    for name, data in parts.items():
        (out_dir / name).write_bytes(data)
    (out_dir / "_not-a-part").write_bytes(b"x")
    c = gateway["client"]
    c.submit("fj", {"input": "in.sam", "output": str(out_dir)})
    stub_transform["release"].set()
    assert gateway["svc"].wait(timeout=30)
    return {"parts": parts, "out_dir": str(out_dir)}


def test_part_listing_and_sha(gateway, fetch_job):
    listing = gateway["client"].list_parts("fj")
    assert listing["state"] == "done"
    got = {p["name"]: p for p in listing["parts"]}
    assert set(got) == set(fetch_job["parts"])  # _not-a-part hidden
    for name, data in fetch_job["parts"].items():
        assert got[name]["bytes"] == len(data)
        assert got[name]["sha256"] == _sha(data)


def test_fetch_resume_and_integrity(gateway, fetch_job, tmp_path):
    c = gateway["client"]
    dest = str(tmp_path / "fetched")
    name = "part-r-00000.parquet"
    data = fetch_job["parts"][name]
    # seed a partial: the first 50k a SIGKILLed client left behind
    os.makedirs(dest)
    with open(os.path.join(dest, name + ".fetch-tmp"), "wb") as fh:
        fh.write(data[:50_000])
    path = c.fetch_part("fj", name, dest)
    assert open(path, "rb").read() == data
    assert not os.path.exists(path + ".fetch-tmp")
    # a corrupt partial (right length prefix, wrong bytes) must NOT
    # publish: the sha check catches it and the retry restarts clean
    os.unlink(path)
    with open(os.path.join(dest, name + ".fetch-tmp"), "wb") as fh:
        fh.write(b"\x00" * 50_000)
    path = c.fetch_part("fj", name, dest)
    assert open(path, "rb").read() == data
    # an already-verified final file short-circuits
    before = os.path.getmtime(path)
    assert c.fetch_part("fj", name, dest) == path
    assert os.path.getmtime(path) == before
    # fetch() gets everything byte-exactly
    dest2 = str(tmp_path / "fetched2")
    fetched = c.fetch("fj", dest2)
    assert set(fetched) == set(fetch_job["parts"])
    for n, p in fetched.items():
        assert open(p, "rb").read() == fetch_job["parts"][n]


def test_fetch_range_protocol_and_containment(gateway, fetch_job):
    name = "part-r-00000.parquet"
    data = fetch_job["parts"][name]
    conn = _raw(gateway)
    conn.request("GET", f"/v1/jobs/fj/parts/{name}",
                 headers={"Range": f"bytes={len(data) - 5}-"})
    r = conn.getresponse()
    assert r.status == 206
    assert r.getheader("Content-Range") == \
        f"bytes {len(data) - 5}-{len(data) - 1}/{len(data)}"
    assert r.getheader(protocol.HDR_PART_SHA256) == _sha(data)
    assert r.read() == data[-5:]
    # start past the end: 416 with the real size for the restart
    conn.request("GET", f"/v1/jobs/fj/parts/{name}",
                 headers={"Range": f"bytes={len(data)}-"})
    r = conn.getresponse()
    assert r.status == 416
    assert r.getheader("Content-Range") == f"bytes */{len(data)}"
    r.read()
    # traversal and non-part names are unservable
    for bad in ("_not-a-part", "..%2F..%2Fetc", "part-..",
                "JOB.json"):
        conn = _raw(gateway)
        conn.request("GET", f"/v1/jobs/fj/parts/{bad}")
        assert conn.getresponse().status == 404, bad
    # fetch bytes are accounted
    from adam_tpu.utils import telemetry as tele

    assert tele.TRACE.snapshot()["counters"].get(
        tele.C_GW_BYTES_OUT, 0
    ) > 0


def test_fetch_resumes_through_midbody_fault(gateway, fetch_job,
                                             tmp_path):
    """A fault that fires AFTER the response headers aborts the
    connection (never a second status line into the framed body); the
    client keeps its partial and resumes via Range — byte-exact."""
    c = gateway["client"]
    name = "part-r-00000.parquet"
    data = fetch_job["parts"][name]
    dest = str(tmp_path / "midbody")
    # chunk 1 ships, the fault kills the connection before chunk 2;
    # the resumed attempt must complete from the 64 KiB partial
    faults.install("gateway.fetch=transient,after=1,times=1")
    try:
        path = c.fetch_part("fj", name, dest)
    finally:
        faults.clear()
    assert open(path, "rb").read() == data


def test_fetch_complete_partial_publishes_without_retransfer(
    gateway, fetch_job, tmp_path,
):
    """A client killed between the last byte and the publish leaves a
    COMPLETE .fetch-tmp: the 416 on its Range probe must verify and
    publish it, not discard it and re-download the whole part."""
    from adam_tpu.utils import telemetry as tele

    c = gateway["client"]
    name = "part-r-00000.parquet"
    data = fetch_job["parts"][name]
    dest = str(tmp_path / "complete")
    os.makedirs(dest)
    with open(os.path.join(dest, name + ".fetch-tmp"), "wb") as fh:
        fh.write(data)
    before = tele.TRACE.snapshot()["counters"].get(
        tele.C_GW_BYTES_OUT, 0
    )
    path = c.fetch_part("fj", name, dest)
    assert open(path, "rb").read() == data
    sent = tele.TRACE.snapshot()["counters"].get(
        tele.C_GW_BYTES_OUT, 0
    ) - before
    assert sent <= 1024, f"re-transferred {sent} bytes of a complete part"


def test_reput_resumes_interrupted_job(gateway, monkeypatch):
    """The cancel verb promises 'a re-submission resumes it': an
    identical re-PUT of an interrupted job must re-admit (201) and
    resume, not short-circuit as an idempotent duplicate."""
    from adam_tpu.pipelines.streamed import RunCancelled

    calls = []

    def fake(inp, out, **kw):
        calls.append(bool(kw.get("resume")))
        if len(calls) == 1:
            raise RunCancelled("cancelled at a window boundary")
        return {"n_reads": 0, "windows_fresh": 0}

    monkeypatch.setattr(sched_mod.streamed_mod, "transform_streamed",
                        fake)
    c = gateway["client"]
    doc = _doc(gateway["tmp"], "ij")
    assert c.submit("ij", doc)["state"] == "pending"
    assert gateway["svc"].wait(timeout=30)
    assert c.status("ij")["state"] == "interrupted"
    again = c.submit("ij", doc)  # NOT a duplicate: a resume
    # the resume KEEPS the original trace — one job, one trace across
    # attempts
    assert again.pop("trace_id")
    assert again == {"job_id": "ij", "state": "pending"}
    assert gateway["svc"].wait(timeout=30)
    assert c.status("ij")["state"] == "done"
    assert calls == [False, True]  # the second run resumed


# ---------------------------------------------------------------------------
# Cancel
# ---------------------------------------------------------------------------
def test_cancel_states(gateway, stub_transform):
    c = gateway["client"]
    tmp = gateway["tmp"]
    with pytest.raises(GatewayError) as ei:
        c.cancel("ghost")
    assert ei.value.status == 404
    c.submit("c1", _doc(tmp, "c1"))
    got = c.cancel("c1")
    assert got == {"job_id": "c1", "cancelling": True}
    stub_transform["release"].set()
    assert gateway["svc"].wait(timeout=30)
    # terminal job: nothing to cancel
    with pytest.raises(GatewayError) as ei:
        c.cancel("c1")
    assert ei.value.status == 409


# ---------------------------------------------------------------------------
# End-to-end over real sockets: two clients, two tenants, real pipeline
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def gw_input(tmp_path_factory):
    """Synthetic input + solo fault-free baseline (numpy backend)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from make_synth_sam import make_sam

    work = tmp_path_factory.mktemp("gateway")
    path = str(work / "in.sam")
    make_sam(path, 4096, 100)
    solo = str(work / "solo.adam")
    os.environ["ADAM_TPU_BQSR_BACKEND"] = "numpy"
    try:
        from adam_tpu.pipelines.streamed import transform_streamed

        transform_streamed(path, solo, window_reads=512)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    return {"input": path, "baseline": _parts_hash(solo)}


def test_two_clients_two_tenants_end_to_end(tmp_path, gw_input,
                                            monkeypatch):
    """The ISSUE-11 acceptance scenario: two jobs submitted
    concurrently by two independent HTTP clients against a live
    gateway, streamed to completion, results downloaded over the wire
    — everything byte-identical to the solo runs."""
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "numpy")
    svc = TransformService(str(tmp_path / "root"), max_jobs=2)
    gw = GatewayServer(svc)
    gw.start()
    outs = {jid: str(tmp_path / f"{jid}.adam") for jid in ("ga", "gb")}
    results = {}
    errors = []

    def one_client(jid, tenant, weight):
        try:
            c = GatewayClient(gw.url)  # each client its own instance
            got = c.submit_with_retry(jid, {
                "input": gw_input["input"], "output": outs[jid],
                "tenant": tenant, "weight": weight,
                "window_reads": 512,
            }, deadline_s=120)
            assert got["state"] == "pending", got
            # follow the event stream to completion (live status via
            # the resumable NDJSON stream, not local file access)
            final = None
            for _cur, line in c.events(jid):
                final = line
            assert final and final.get("done"), final
            assert final.get("ok") is True, final
            # download the results over the wire
            dest = str(tmp_path / f"fetched-{jid}")
            fetched = c.fetch(jid, dest)
            results[jid] = {
                "final": final,
                "fetched": {
                    n: _sha(open(p, "rb").read())
                    for n, p in fetched.items()
                },
            }
        except Exception as e:  # surfaced by the main thread
            errors.append((jid, e))

    threads = [
        threading.Thread(target=one_client, args=("ga", "A", 2.0)),
        threading.Thread(target=one_client, args=("gb", "B", 1.0)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
        assert not t.is_alive(), "client thread hung"
    assert not errors, errors
    assert svc.wait(timeout=60)
    for jid in outs:
        # the job's server-side output is byte-identical to solo...
        assert _parts_hash(outs[jid]) == gw_input["baseline"], jid
        # ...and so is every part the client downloaded over HTTP
        assert results[jid]["fetched"] == gw_input["baseline"], jid
    # remote top renders the finished board and exits clean
    from adam_tpu.utils import top as top_mod

    assert top_mod.follow_url(gw.url, once=True,
                              out=open(os.devnull, "w")) == 0
    assert top_mod.follow_url(gw.url, interval=0.1, max_wait_s=30,
                              out=open(os.devnull, "w")) == 0
    gw.close()
    svc.close()


def test_top_url_unreachable_exits_2():
    from adam_tpu.utils import top as top_mod

    # a port nothing listens on: exit 2, the no-stream contract
    assert top_mod.follow_url("http://127.0.0.1:9", once=True,
                              out=open(os.devnull, "w")) == 2


# ---------------------------------------------------------------------------
# SIGTERM drain ordering through the real CLI (subprocess)
# ---------------------------------------------------------------------------
_DRIVER = """\
import sys
try:
    import jax, jax._src.xla_bridge as xb
    xb._backend_factories.pop('axon', None)
    jax.config.update('jax_platforms', 'cpu')
except Exception:
    pass
from adam_tpu.cli.main import main
sys.exit(main(sys.argv[1:]))
"""


def _gw_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ADAM_TPU_BQSR_BACKEND"] = "numpy"
    env.setdefault("ADAM_TPU_NO_COMPILE_CACHE", "1")
    env["ADAM_TPU_PROGRESS_INTERVAL_S"] = "0.2"
    env.pop("ADAM_TPU_FAULTS", None)
    return env


def test_serve_listen_sigterm_drain_exit0(tmp_path, gw_input):
    """SIGTERM a live gateway: stop accepting -> 503 -> scheduler
    drain -> settled -> exit 0 (docs/SERVING.md drain ordering), with
    every JOB.json durably terminal and the run resumable."""
    root = str(tmp_path / "root")
    out = str(tmp_path / "sj.adam")
    proc = subprocess.Popen(
        [sys.executable, "-c", _DRIVER, "serve", root,
         "--listen", "127.0.0.1:0", "--max-jobs", "2"],
        env=_gw_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        # discovery: gateway.json appears once the socket is bound
        deadline = time.monotonic() + 60
        gw_json = os.path.join(root, "gateway.json")
        while time.monotonic() < deadline:
            if os.path.isfile(gw_json):
                break
            assert proc.poll() is None, \
                proc.communicate()[0].decode(errors="replace")
            time.sleep(0.05)
        c = GatewayClient(resolve_url(root))
        got = c.submit_with_retry("sj", {
            "input": gw_input["input"], "output": out,
            "window_reads": 512,
        }, deadline_s=60)
        assert got["state"] == "pending"
        # wait for the job to be genuinely mid-flight, then SIGTERM
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if os.path.isfile(os.path.join(root, "sj",
                                           "heartbeat.ndjson")):
                break
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        stdout, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, stdout.decode(errors="replace")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    # settled: the job's JOB.json is durably terminal
    doc = json.load(open(os.path.join(root, "sj", "JOB.json")))
    assert doc["state"] in ("done", "interrupted")
    # a rerun (recover + resume, no gateway needed) completes the job
    # byte-identically if the drain interrupted it
    rc = subprocess.run(
        [sys.executable, "-c", _DRIVER, "serve", root],
        env=_gw_env(), cwd=REPO, capture_output=True,
    )
    assert rc.returncode == 0, rc.stdout + rc.stderr
    assert _parts_hash(out) == gw_input["baseline"]
    doc = json.load(open(os.path.join(root, "sj", "JOB.json")))
    assert doc["state"] == "done"


# ---------------------------------------------------------------------------
# Observability surfaces: /metrics, /v1/jobs/<id>/trace, /incidents
# (docs/OBSERVABILITY.md "Gateway observability surfaces")
# ---------------------------------------------------------------------------
def _scrape_value(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split(" ", 1)[1])
    return None


def test_metrics_scrapes_exposition_and_monotonic(gateway,
                                                  stub_transform):
    from adam_tpu.utils import telemetry as tele

    c = gateway["client"]
    first = c.metrics()
    second = c.metrics()
    for text in (first, second):
        assert text.endswith("\n")
        # every non-comment sample line is name[{labels}] value, the
        # name valid per the exposition grammar
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            name = line.split("{")[0].split(" ")[0]
            assert tele.prometheus_name_valid(name), line
        assert "# TYPE adam_tpu_gateway_metrics_scrapes counter" in text
        assert "adam_tpu_traces_active" in text
    # each scrape counts ITSELF before snapshotting, so consecutive
    # scrapes read strictly increasing adam_tpu_gateway_metrics_scrapes
    s1 = _scrape_value(first, "adam_tpu_gateway_metrics_scrapes")
    s2 = _scrape_value(second, "adam_tpu_gateway_metrics_scrapes")
    assert s1 is not None and s2 is not None and s2 > s1
    # gateway.requests surfaces too — it counts in the handler's
    # finally AFTER the response is written, so allow the bump from an
    # earlier scrape a moment to land
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        v = _scrape_value(c.metrics(), "adam_tpu_gateway_requests")
        if v is not None and v >= 1:
            break
        time.sleep(0.05)
    else:
        pytest.fail("gateway.requests never surfaced in /metrics")


def test_job_trace_endpoint_and_trace_id_echo(gateway, stub_transform):
    import re

    from adam_tpu.utils import telemetry as tele

    c = gateway["client"]
    tmp = gateway["tmp"]
    got = c.submit("tj", _doc(tmp, "tj"))
    tid = got["trace_id"]
    assert re.fullmatch(r"[0-9a-f]{16}", tid)
    # a duplicate-safe re-PUT echoes the SAME trace: one job, one trace
    again = c.submit("tj", _doc(tmp, "tj"))
    assert again["duplicate"] is True and again["trace_id"] == tid
    stub_transform["release"].set()
    assert gateway["svc"].wait(timeout=30)
    doc = c.job_trace("tj")
    assert doc["job_id"] == "tj" and doc["trace_id"] == tid
    names = {e["name"] for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    # the trace spans the job's lifecycle: the gateway submit root and
    # the scheduler's run umbrella (the stub replaces the streamed leg)
    assert tele.SPAN_GW_SUBMIT in names
    assert tele.SPAN_SCHED_JOB in names
    # every X event in the filtered view belongs to this trace —
    # stamped or linked, never a stranger
    for e in doc["traceEvents"]:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        assert args.get("trace") == tid or any(
            l.get("trace") == tid for l in args.get("links") or []
        ), e
    # unknown job: typed 404
    with pytest.raises(GatewayError) as ei:
        c.job_trace("nope")
    assert ei.value.status == 404


def test_incidents_endpoint_lists_run_root_bundles(gateway,
                                                   stub_transform):
    from adam_tpu.utils import incidents as incidents_mod

    c = gateway["client"]
    empty = c.incidents()
    assert empty["schema"] == protocol.INCIDENTS_SCHEMA
    assert empty["incidents"] == []
    # the serve ctor armed the recorder on its run root: a trigger
    # fired anywhere in-process surfaces on the wire
    assert incidents_mod.incidents_dir() == os.path.join(
        gateway["root"], incidents_mod.INCIDENTS_DIRNAME
    )
    path = incidents_mod.maybe_record(
        "hedge.fired", reason="wire-visibility probe"
    )
    assert path is not None
    rows = c.incidents()["incidents"]
    assert [r["trigger"] for r in rows] == ["hedge.fired"]
    assert rows[0]["reason"] == "wire-visibility probe"
