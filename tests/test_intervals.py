"""Region join / coverage / pairing tests.

Differential style mirrors the reference suites (ReferenceRegionSuite,
BroadcastRegionJoinSuite, ShuffleRegionJoinSuite, CoverageSuite,
PairingRDDSuite): vectorized results are checked against brute-force
O(n^2) oracles on randomized inputs, plus the documented examples.
"""

import numpy as np
import pytest

from adam_tpu.models.dictionaries import SequenceDictionary
from adam_tpu.ops import intervals as iv
from adam_tpu.pipelines.region_join import (
    GenomeBins,
    IntervalArrays,
    NonoverlappingRegions,
    broadcast_region_join,
    depth_at,
    find_coverage_regions,
    pair,
    pair_with_ends,
    shuffle_region_join,
    sliding,
)


def random_intervals(rng, n, n_contigs=3, span=1000, max_len=120):
    contig = rng.integers(0, n_contigs, n)
    start = rng.integers(0, span, n)
    length = rng.integers(1, max_len, n)
    return IntervalArrays.of(contig, start, start + length)


def brute_overlap_pairs(l, r):
    pairs = set()
    for i in range(len(l)):
        for j in range(len(r)):
            if (
                l.contig[i] == r.contig[j]
                and l.end[i] > r.start[j]
                and r.end[j] > l.start[i]
            ):
                pairs.add((i, j))
    return pairs


class TestMerge:
    def test_merges_overlapping_and_adjacent(self):
        m_c, m_s, m_e, grp = iv.merge_intervals(
            [0, 0, 0, 1], [10, 15, 30, 5], [20, 25, 40, 9]
        )
        assert m_s.tolist() == [10, 30, 5]
        assert m_e.tolist() == [25, 40, 9]
        assert m_c.tolist() == [0, 0, 1]
        assert grp.tolist() == [0, 0, 1, 2]

    def test_adjacent_flag(self):
        # [10,20) and [20,30) touch: merged when adjacent=True, else not
        _, s, e, _ = iv.merge_intervals([0, 0], [10, 20], [20, 30])
        assert s.tolist() == [10] and e.tolist() == [30]
        _, s, e, _ = iv.merge_intervals(
            [0, 0], [10, 20], [20, 30], adjacent=False
        )
        assert s.tolist() == [10, 20]

    def test_contained_interval(self):
        _, s, e, _ = iv.merge_intervals([0, 0, 0], [0, 5, 8], [100, 9, 12])
        assert s.tolist() == [0] and e.tolist() == [100]

    def test_random_against_bruteforce(self):
        rng = np.random.default_rng(0)
        for _ in range(5):
            ivs = random_intervals(rng, 60)
            m_c, m_s, m_e, grp = iv.merge_intervals(ivs.contig, ivs.start, ivs.end)
            # every input is inside its group
            assert np.all(m_s[grp] <= ivs.start)
            assert np.all(m_e[grp] >= ivs.end)
            # groups disjoint and non-adjacent within contig
            same = m_c[1:] == m_c[:-1]
            assert np.all(m_s[1:][same] > m_e[:-1][same])
            # total covered bases match a brute-force union
            covered = set()
            for i in range(len(ivs)):
                for p in range(ivs.start[i], ivs.end[i]):
                    covered.add((ivs.contig[i], p))
            merged_cover = int(np.sum(m_e - m_s))
            assert merged_cover == len(covered)


class TestBroadcastJoin:
    def test_small_example(self):
        left = IntervalArrays.of([0, 0], [100, 500], [200, 600])
        right = IntervalArrays.of([0, 0, 1], [150, 590, 150], [160, 700, 160])
        li, ri = broadcast_region_join(left, right)
        assert set(zip(li.tolist(), ri.tolist())) == {(0, 0), (1, 1)}

    def test_random_against_bruteforce(self):
        rng = np.random.default_rng(1)
        for _ in range(5):
            l = random_intervals(rng, 40)
            r = random_intervals(rng, 55)
            li, ri = broadcast_region_join(l, r)
            got = set(zip(li.tolist(), ri.tolist()))
            assert len(got) == len(li), "duplicate pairs emitted"
            assert got == brute_overlap_pairs(l, r)

    def test_nonoverlapping_regions_index(self):
        regs = IntervalArrays.of([0, 0, 0], [10, 15, 40], [20, 25, 50])
        idx = NonoverlappingRegions(regs)
        assert len(idx) == 2
        q = IntervalArrays.of([0, 0, 0, 1], [0, 22, 30, 12], [5, 23, 35, 18])
        has = idx.has_regions_for(q)
        assert has.tolist() == [False, True, False, False]

    def test_empty_sides(self):
        l = IntervalArrays.of([], [], [])
        r = IntervalArrays.of([0], [0], [10])
        li, ri = broadcast_region_join(l, r)
        assert len(li) == 0
        li, ri = broadcast_region_join(r, l)
        assert len(li) == 0


class TestShuffleJoin:
    def make_dict(self):
        return SequenceDictionary.from_lists(
            ["chr1", "chr2", "chr3"], [2000, 2000, 2000]
        )

    def test_matches_broadcast_join(self):
        rng = np.random.default_rng(2)
        sd = self.make_dict()
        for bin_size in (100, 256, 5000):
            l = random_intervals(rng, 50, span=1800)
            r = random_intervals(rng, 50, span=1800)
            li, ri = shuffle_region_join(l, r, sd, bin_size)
            got = set(zip(li.tolist(), ri.tolist()))
            assert len(got) == len(li), "dedupe rule failed"
            assert got == brute_overlap_pairs(l, r)

    def test_overhanging_interval_no_duplicates(self):
        # interval extending past the declared contig length must not
        # spill into the next contig's bin range and duplicate pairs
        sd = SequenceDictionary.from_lists(["c1", "c2"], [2000, 2000])
        l = IntervalArrays.of([0], [1950], [3100])
        r = IntervalArrays.of([0], [1960], [3050])
        li, ri = shuffle_region_join(l, r, sd, bin_size=1000)
        assert list(zip(li.tolist(), ri.tolist())) == [(0, 0)]

    def test_zero_length_contig_still_joins(self):
        # contigs with undeclared (0) length own one bin; their pairs
        # survive, including when both sides start past the bin size
        sd = SequenceDictionary.from_lists(["c0", "c1", "c2"], [0, 2000, 0])
        l = IntervalArrays.of([0, 2, 2], [10, 5000, 9000], [20, 5100, 9100])
        r = IntervalArrays.of([0, 2], [15, 5050], [25, 5150])
        li, ri = shuffle_region_join(l, r, sd, bin_size=1000)
        assert set(zip(li.tolist(), ri.tolist())) == {(0, 0), (1, 1)}

    def test_genome_bins(self):
        sd = self.make_dict()
        bins = GenomeBins(1000, sd)
        assert bins.num_bins == 6
        assert bins.start_bin(1, 0) == 2
        assert bins.end_bin(0, 1000) == 0  # end exclusive: last base 999
        assert bins.invert(3) == (1, 1000, 2000)
        # spanning interval covers two bins
        lo = bins.start_bin(np.array([0]), np.array([900]))
        hi = bins.end_bin(np.array([0]), np.array([1100]))
        assert lo.tolist() == [0] and hi.tolist() == [1]


class TestCoverage:
    def test_documented_semantics(self):
        # covered bases only, minimal, non-adjacent regions collapse
        regs = IntervalArrays.of(
            [0, 0, 0, 0], [10, 15, 25, 40], [20, 25, 30, 50]
        )
        cov = find_coverage_regions(regs)
        assert cov.start.tolist() == [10, 40]
        assert cov.end.tolist() == [30, 50]

    def test_random_against_bruteforce(self):
        rng = np.random.default_rng(3)
        ivs = random_intervals(rng, 80)
        cov = find_coverage_regions(ivs)
        covered = set()
        for i in range(len(ivs)):
            for p in range(ivs.start[i], ivs.end[i]):
                covered.add((int(ivs.contig[i]), int(p)))
        got = set()
        for i in range(len(cov)):
            for p in range(cov.start[i], cov.end[i]):
                got.add((int(cov.contig[i]), int(p)))
        assert got == covered

    def test_depth_at(self):
        reads = IntervalArrays.of([0, 0, 0], [0, 5, 5], [10, 15, 8])
        sites = IntervalArrays.of([0, 0, 0, 1], [6, 12, 20, 6], [7, 13, 21, 7])
        d = depth_at(sites, reads)
        assert d.tolist() == [3, 1, 0, 0]


class TestPairing:
    def test_sliding(self):
        w = sliding(np.array([1, 2, 3, 4, 5]), 3)
        assert w.tolist() == [[1, 2, 3], [2, 3, 4], [3, 4, 5]]
        assert sliding(np.array([1, 2]), 3).shape == (0, 3)

    def test_pair(self):
        a, b = pair(np.array([1, 2, 3, 4]))
        assert list(zip(a.tolist(), b.tolist())) == [(1, 2), (2, 3), (3, 4)]

    def test_pair_with_ends(self):
        got = pair_with_ends(np.array([1, 2, 3]))
        assert got == [(None, 1), (1, 2), (2, 3), (3, None)]
        assert pair_with_ends(np.array([])) == []


class TestShardedJoin:
    """Out-of-core genome-bin shard join/depth (parallel/sharded_join):
    bit-parity with the monolithic joins, including intervals spanning
    bin edges (halo replication) and multi-window streams."""

    def _stream(self, rng, n, seq_dict, window=137):
        """Random read-shaped batches -> list of (batch, None, None)
        triples plus the concatenated interval view."""
        from adam_tpu.formats.batch import ReadBatch

        n_contigs = len(seq_dict.names)
        contig = rng.integers(0, n_contigs, n).astype(np.int32)
        start = rng.integers(0, 4000, n).astype(np.int64)
        length = rng.integers(1, 900, n).astype(np.int64)  # spans bins
        batches = []
        for lo in range(0, n, window):
            hi = min(lo + window, n)
            m = hi - lo
            b = ReadBatch.empty().pad_rows(m)
            b = b.replace(
                contig_idx=contig[lo:hi],
                start=start[lo:hi],
                end=start[lo:hi] + length[lo:hi],
                flags=np.zeros(m, np.int32),  # mapped
                valid=np.ones(m, bool),
            )
            batches.append((b, None, None))
        return batches, IntervalArrays.of(contig, start, start + length)

    def test_streamed_depth_parity(self, tmp_path):
        from adam_tpu.parallel.sharded_join import streamed_depth

        rng = np.random.default_rng(5)
        seq_dict = SequenceDictionary.from_lists(
            ["chr1", "chr2", "chr3"], [5000, 2500, 700]
        )
        batches, reads = self._stream(rng, 500, seq_dict)
        sites = IntervalArrays.of(
            rng.integers(0, 3, 200),
            rng.integers(0, 5200, 200),
            rng.integers(0, 5200, 200) + 1,
        )
        got = streamed_depth(
            iter(batches), sites, seq_dict, bin_size=1000,
            workdir=str(tmp_path / "spill"),
        )
        want = iv.point_depth(
            reads.contig, reads.start, reads.end,
            sites.contig, sites.start,
        )
        np.testing.assert_array_equal(got, want)

    def test_streamed_overlap_join_parity(self, tmp_path):
        from adam_tpu.parallel.sharded_join import streamed_overlap_join

        rng = np.random.default_rng(9)
        seq_dict = SequenceDictionary.from_lists(["chr1", "chr2"], [5000, 2500])
        batches, reads = self._stream(rng, 400, seq_dict)
        right = random_intervals(rng, 150, n_contigs=2, span=4500,
                                 max_len=1500)
        pairs = []
        for gl, gr in streamed_overlap_join(
            iter(batches), right, seq_dict, bin_size=1000,
            workdir=str(tmp_path / "spill"),
        ):
            pairs += [(int(a), int(b)) for a, b in zip(gl, gr)]
        # pair-set parity with the fully-resident join; no halo dupes
        assert len(pairs) == len(set(pairs))
        li, ri = iv.overlap_join(
            reads.contig, reads.start, reads.end,
            right.contig, right.start, right.end,
        )
        want = set(zip(li.tolist(), ri.tolist()))
        assert set(pairs) == want

    def test_depth_cli_stream_matches_monolithic(self, tmp_path, capsys):
        """`depth -stream` prints byte-identical output to the resident
        join on the same inputs."""
        from adam_tpu.cli.main import main
        from adam_tpu.formats.batch import ReadBatch, ReadSidecar
        from adam_tpu.io.sam import SamHeader, write_sam

        rng = np.random.default_rng(3)
        n = 300
        seq_dict = SequenceDictionary.from_lists(["chr1", "chr2"], [4000, 1500])
        contig = rng.integers(0, 2, n).astype(np.int32)
        start = rng.integers(0, 3500, n).astype(np.int64)
        length = rng.integers(30, 600, n).astype(np.int64)
        b = ReadBatch.empty().pad_rows(n).replace(
            contig_idx=contig, start=start, end=start + length,
            flags=np.zeros(n, np.int32), valid=np.ones(n, bool),
            cigar_n=np.zeros(n, np.int32),
            mapq=np.full(n, 60, np.int32),
        )
        side = ReadSidecar(
            names=[f"r{i}" for i in range(n)], attrs=[""] * n,
            md=[None] * n, orig_quals=[None] * n,
        )
        header = SamHeader(seq_dict=seq_dict)
        sam = str(tmp_path / "reads.sam")
        write_sam(sam, b, side, header)
        vcf = str(tmp_path / "sites.vcf")
        with open(vcf, "w") as fh:
            fh.write("##fileformat=VCFv4.1\n")
            fh.write("##contig=<ID=chr1,length=4000>\n")
            fh.write("##contig=<ID=chr2,length=1500>\n")
            fh.write("#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
            for k in range(40):
                c = ["chr1", "chr2"][k % 2]
                pos = int(rng.integers(1, 3500 if k % 2 == 0 else 1400))
                fh.write(f"{c}\t{pos}\trs{k}\tA\tG\t50\tPASS\t.\n")
        assert main(["depth", sam, vcf]) == 0
        plain = capsys.readouterr().out
        assert main(["depth", "-stream", "-bin_size", "700", sam, vcf]) == 0
        streamed = capsys.readouterr().out
        assert streamed == plain
        assert "depth" in plain
