import numpy as np
import pytest

from adam_tpu.models import (
    ReferencePosition,
    ReferenceRegion,
    SequenceDictionary,
    SequenceRecord,
    RecordGroupDictionary,
    RecordGroup,
)
from adam_tpu.models.positions import pack_position_key, unpack_position_key


def test_region_overlaps_and_merge():
    a = ReferenceRegion("chr1", 10, 20)
    b = ReferenceRegion("chr1", 15, 25)
    c = ReferenceRegion("chr1", 20, 30)
    d = ReferenceRegion("chr2", 10, 20)
    assert a.overlaps(b)
    assert not a.overlaps(c)  # end-exclusive
    assert a.is_adjacent(c)
    assert not a.overlaps(d)
    assert a.merge(b) == ReferenceRegion("chr1", 10, 25)
    assert a.merge(c) == ReferenceRegion("chr1", 10, 30)
    with pytest.raises(ValueError):
        a.merge(ReferenceRegion("chr1", 50, 60))
    assert a.hull(ReferenceRegion("chr1", 50, 60)) == ReferenceRegion("chr1", 10, 60)
    assert a.intersection(b) == ReferenceRegion("chr1", 15, 20)
    assert a.distance(ReferenceRegion("chr1", 40, 50)) == 21
    assert a.distance(d) is None


def test_region_contains_point_ordering():
    r = ReferenceRegion("chr1", 10, 20)
    assert r.contains_point(ReferencePosition("chr1", 10))
    assert not r.contains_point(ReferencePosition("chr1", 20))
    assert ReferencePosition("chr1", 5) < ReferencePosition("chr1", 6)
    assert ReferencePosition("chr1", 5) < ReferencePosition("chr2", 0)


def test_position_key_roundtrip():
    c = np.array([0, 3, -1], dtype=np.int32)
    p = np.array([123456789, 0, 0], dtype=np.int64)
    keys = pack_position_key(c, p)
    assert keys.dtype == np.int64
    # ordering: contig-major then position
    assert keys[0] < pack_position_key(np.int32(0), np.int64(123456790))
    assert keys[0] < keys[1]
    assert keys[2] < keys[0]  # unmapped packs lowest
    cc, pp = unpack_position_key(keys)
    np.testing.assert_array_equal(cc, c)
    np.testing.assert_array_equal(pp[:2], p[:2])


def _dict():
    return SequenceDictionary(
        (SequenceRecord("1", 1000), SequenceRecord("2", 500))
    )


def test_sequence_dictionary_basic():
    sd = _dict()
    assert len(sd) == 2
    assert "1" in sd and "3" not in sd
    assert sd.index("2") == 1
    assert sd.index_or("zz") == -1
    np.testing.assert_array_equal(sd.offsets, [0, 1000, 1500])
    assert sd.total_length == 1500


def test_sequence_dictionary_merge():
    sd = _dict()
    other = SequenceDictionary((SequenceRecord("2", 500), SequenceRecord("3", 42)))
    merged = sd.merge(other)
    assert merged.names == ["1", "2", "3"]
    bad = SequenceDictionary((SequenceRecord("2", 999),))
    assert not sd.is_compatible_with(bad)
    with pytest.raises(ValueError):
        sd.merge(bad)


def test_sequence_dictionary_sam_header_roundtrip():
    lines = ["@SQ\tSN:chrM\tLN:16571\tAS:hg19", "@HD\tVN:1.5"]
    sd = SequenceDictionary.from_sam_header_lines(lines)
    assert sd.names == ["chrM"]
    assert sd["chrM"].length == 16571
    assert sd["chrM"].assembly == "hg19"
    out = sd.to_sam_header_lines()
    assert out == ["@SQ\tSN:chrM\tLN:16571\tAS:hg19"]


def test_record_groups():
    rgd = RecordGroupDictionary.from_sam_header_lines(
        [
            "@RG\tID:rg1\tSM:s1\tLB:libA",
            "@RG\tID:rg2\tSM:s1\tLB:libA",
            "@RG\tID:rg3\tSM:s2\tLB:libB",
        ]
    )
    assert rgd.names == ["rg1", "rg2", "rg3"]
    libs = rgd.library_ids()
    assert libs[0] == libs[1] != libs[2]
    assert rgd.index("rg3") == 2
    merged = rgd.merge(RecordGroupDictionary((RecordGroup("rg4"),)))
    assert len(merged) == 4
    with pytest.raises(ValueError):
        rgd.merge(
            RecordGroupDictionary((RecordGroup("rg1", library="other"),))
        )
