"""Telemetry layer: spans, counters, gauges, flight recorder, exports.

The contract under test (utils/telemetry.py, docs/OBSERVABILITY.md):
recording is lossless under concurrent writers (the ``TimerRegistry``
lock discipline), the flight recorder is bounded and evicts oldest
first, the JSON / Chrome-trace exports round-trip, and the streamed
pipeline's ``stats`` dict is a pure derived view of its span data —
recomputing the view from an exported snapshot reproduces it exactly.
"""

import json
import os
import re
import sys
import threading

import pytest

from adam_tpu.utils import instrumentation as ins
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


@pytest.fixture(autouse=True)
def _clean_global_state():
    """Tests toggle the process-wide TRACE/TIMERS; leave them as found."""
    rec_t, rec_i = tele.TRACE.recording, ins.TIMERS.recording
    yield
    tele.TRACE.recording = rec_t
    ins.TIMERS.recording = rec_i
    tele.TRACE.reset()
    ins.TIMERS.recording = True
    ins.TIMERS.reset()
    ins.TIMERS.recording = rec_i


# --------------------------------------------------------------------------
# core recorder
# --------------------------------------------------------------------------
def test_disabled_tracer_records_nothing():
    tr = tele.Tracer(recording=False)
    with tr.span(tele.SPAN_TOKENIZE, window=0):
        pass
    tr.count(tele.C_READS_INGESTED, 100)
    tr.gauge(tele.G_POOL_DEPTH, 3)
    snap = tr.snapshot()
    assert snap["spans"] == {} and snap["counters"] == {}
    assert snap["gauges"] == {} and snap["events_recorded"] == 0
    # the disabled fast path hands back one shared no-op object
    assert tr.span(tele.SPAN_SOLVE) is tr.span(tele.SPAN_TOKENIZE)


def test_concurrent_recording_is_lossless():
    """≥4 threads hammering spans+counters+gauges: nothing lost."""
    tr = tele.Tracer(recording=True, capacity=1 << 16)
    n_threads, per_thread = 8, 500
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(per_thread):
            with tr.span(tele.SPAN_TOKENIZE, window=i):
                pass
            tr.count(tele.C_READS_INGESTED, 2)
            tr.gauge(tele.G_POOL_DEPTH, tid)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    # concurrent readers must not race the writers (satellite: locked
    # snapshot) — exercise while recording is in flight
    for _ in range(50):
        tr.snapshot()
        tr.span_seconds()
    for t in threads:
        t.join()
    snap = tr.snapshot()
    total = n_threads * per_thread
    assert snap["spans"][tele.SPAN_TOKENIZE]["count"] == total
    assert snap["counters"][tele.C_READS_INGESTED] == 2 * total
    assert snap["gauges"][tele.G_POOL_DEPTH]["n"] == total
    assert snap["gauges"][tele.G_POOL_DEPTH]["min"] == 0
    assert snap["gauges"][tele.G_POOL_DEPTH]["max"] == n_threads - 1
    assert snap["events_recorded"] == total
    assert snap["events_evicted"] == 0


def test_ring_buffer_evicts_oldest_keeps_newest():
    tr = tele.Tracer(recording=True, capacity=16)
    t0 = 1_000_000
    for i in range(100):
        tr.add_span(tele.SPAN_TOKENIZE, t0 + i, 10, window=i)
    evs = tr.events()
    assert len(evs) == 16
    # newest 16 survive, oldest first within the ring
    assert [e["args"]["window"] for e in evs] == list(range(84, 100))
    snap = tr.snapshot()
    assert snap["events_recorded"] == 100
    assert snap["events_retained"] == 16
    assert snap["events_evicted"] == 84
    # aggregates live OUTSIDE the ring: totals stay exact post-eviction
    assert snap["spans"][tele.SPAN_TOKENIZE]["count"] == 100


def test_span_nesting_records_parent_and_thread():
    tr = tele.Tracer(recording=True)
    with tr.span(tele.SPAN_PASS_C):
        with tr.span(tele.SPAN_APPLY_DISPATCH, window=3):
            pass
    evs = tr.events()
    # inner exits (and records) first
    assert [e["name"] for e in evs] == [
        tele.SPAN_APPLY_DISPATCH, tele.SPAN_PASS_C,
    ]
    assert evs[0]["parent"] == tele.SPAN_PASS_C
    assert evs[0]["args"]["window"] == 3
    assert "parent" not in evs[1]
    assert evs[0]["thread"] == threading.current_thread().name


def test_absorb_merges_aggregates_and_events():
    a = tele.Tracer(recording=True)
    b = tele.Tracer(recording=True)
    for tr, k in ((a, 1), (b, 2)):
        for _ in range(k):
            with tr.span(tele.SPAN_SOLVE):
                pass
        tr.count(tele.C_PARTS_WRITTEN, k)
        tr.gauge(tele.G_DEVICE_INFLIGHT, k)
    a.absorb(b)
    snap = a.snapshot()
    assert snap["spans"][tele.SPAN_SOLVE]["count"] == 3
    assert snap["counters"][tele.C_PARTS_WRITTEN] == 3
    g = snap["gauges"][tele.G_DEVICE_INFLIGHT]
    assert (g["min"], g["max"], g["n"], g["last"]) == (1, 2, 2, 2)
    assert snap["events_recorded"] == 3


# --------------------------------------------------------------------------
# exports round-trip
# --------------------------------------------------------------------------
def _populated_tracer():
    tr = tele.Tracer(recording=True)
    with tr.span(tele.SPAN_PASS_A):
        with tr.span(tele.SPAN_TOKENIZE, window=0):
            pass
    tr.count(tele.C_WINDOWS_INGESTED)
    tr.gauge(tele.G_POOL_DEPTH, 2)
    return tr


def test_json_export_round_trips(tmp_path):
    tr = _populated_tracer()
    ins.TIMERS.recording = True
    ins.TIMERS.reset()
    ins.TIMERS.add(ins.SAM_ENCODE, 2_000_000_000)
    p = str(tmp_path / "m.json")
    tr.dump_json(p, include_events=True)
    doc = json.load(open(p))
    assert doc["meta"]["schema"] == "adam_tpu.telemetry/1"
    # the snapshot sections survive the file round-trip verbatim
    snap = tr.snapshot()
    assert doc["spans"] == snap["spans"]
    assert doc["counters"] == snap["counters"]
    assert doc["gauges"] == snap["gauges"]
    # the timers section is the TimerRegistry snapshot, same rows the
    # printed table carries
    assert doc["timers"][ins.SAM_ENCODE] == {"count": 1, "total_s": 2.0}
    # include_events carries the flight recorder
    assert [e["name"] for e in doc["events"]] == [
        e["name"] for e in tr.events()
    ]


def test_chrome_trace_export_loads_and_tracks_threads(tmp_path):
    tr = _populated_tracer()

    def other_thread():
        with tr.span(tele.SPAN_PART_ENCODE, rows=8):
            pass

    t = threading.Thread(target=other_thread, name="pw-enc-0")
    t.start()
    t.join()
    p = str(tmp_path / "t.json")
    tr.dump_chrome_trace(p)
    doc = json.load(open(p))
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    # one thread_name metadata record per recording thread, distinct tids
    names = {e["args"]["name"] for e in meta}
    assert "pw-enc-0" in names and len(names) == 2
    assert len({e["tid"] for e in meta}) == 2
    # complete events carry microsecond ts/dur on the right track
    by_name = {e["name"]: e for e in spans}
    assert set(by_name) == {
        tele.SPAN_PASS_A, tele.SPAN_TOKENIZE, tele.SPAN_PART_ENCODE,
    }
    ring = {e["name"]: e for e in tr.events()}
    for name, ev in by_name.items():
        assert ev["dur"] == pytest.approx(ring[name]["dur_ns"] / 1e3)
    enc_tid = by_name[tele.SPAN_PART_ENCODE]["tid"]
    tok_tid = by_name[tele.SPAN_TOKENIZE]["tid"]
    assert enc_tid != tok_tid
    # nesting attribution survives as args.parent
    assert by_name[tele.SPAN_TOKENIZE]["args"]["parent"] == tele.SPAN_PASS_A


def test_key_stable_snapshot_zero_fills_device_metrics():
    tr = tele.Tracer(recording=True)
    tr.count(tele.C_READS_INGESTED, 5)
    snap = tele.key_stable_snapshot(tr)
    for name in tele.DEVICE_ONLY_COUNTERS:
        assert snap["counters"][name] == 0
    for name in tele.DEVICE_ONLY_GAUGES:
        assert snap["gauges"][name] == {
            "last": 0, "min": 0, "max": 0, "n": 0,
        }
    # real values are never clobbered by the zero-fill
    assert snap["counters"][tele.C_READS_INGESTED] == 5


def test_merge_snapshots_reports_per_host_skew():
    def host(total_s):
        tr = tele.Tracer(recording=True)
        tr.add_span(tele.SPAN_PASS_A, 0, int(total_s * 1e9))
        return tr.snapshot()

    merged = tele.merge_snapshots([host(1.0), host(3.0)])
    assert merged["n_hosts"] == 2
    sk = merged["span_skew"][tele.SPAN_PASS_A]
    assert sk["min_s"] == pytest.approx(1.0)
    assert sk["max_s"] == pytest.approx(3.0)


# --------------------------------------------------------------------------
# TimerRegistry satellites
# --------------------------------------------------------------------------
def test_timer_snapshot_safe_during_recording():
    reg = ins.TimerRegistry(recording=True)
    stop = threading.Event()

    def hammer(i):
        while not stop.is_set():
            with reg.time(f"t{i}"):
                pass

    threads = [
        threading.Thread(target=hammer, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            snap = reg.snapshot()
            for name, (count, total_ns) in snap.items():
                assert count >= 1 and total_ns >= 0
            reg.report()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert set(reg.snapshot()) == {f"t{i}" for i in range(4)}


def test_timers_reset_clears_telemetry_metrics():
    ins.TIMERS.recording = True
    tele.TRACE.recording = True
    ins.TIMERS.add(ins.SAM_ENCODE, 1000)
    tele.TRACE.count(tele.C_PARTS_WRITTEN, 7)
    tele.TRACE.gauge(tele.G_POOL_DEPTH, 4)
    ins.TIMERS.reset()
    assert ins.TIMERS.snapshot() == {}
    snap = tele.TRACE.snapshot()
    # one reset clears the whole metrics surface (satellite 1)
    assert snap["counters"] == {} and snap["gauges"] == {}


def test_private_registry_reset_leaves_global_telemetry_alone():
    tele.TRACE.recording = True
    tele.TRACE.count(tele.C_PARTS_WRITTEN, 3)
    reg = ins.TimerRegistry(recording=True)
    reg.add(ins.SAM_ENCODE, 1000)
    reg.reset()
    assert reg.snapshot() == {}
    # only the process-global TIMERS reset cascades into TRACE
    assert tele.TRACE.snapshot()["counters"][tele.C_PARTS_WRITTEN] == 3


def test_device_trace_reentrant_noop(tmp_path, caplog, monkeypatch):
    """A second concurrent device_trace warns and no-ops instead of
    crashing the profiler (satellite 2)."""
    monkeypatch.setattr(ins, "_DEVICE_TRACE_ACTIVE", True)
    with caplog.at_level("WARNING", logger="adam_tpu.utils.instrumentation"):
        with ins.device_trace(str(tmp_path / "xprof")):
            pass
    assert any("already active" in r.message for r in caplog.records)
    # the no-op inner exit must NOT release the outer trace's guard
    assert ins._DEVICE_TRACE_ACTIVE is True


# --------------------------------------------------------------------------
# streamed pipeline: stats is a derived view of the span data
# --------------------------------------------------------------------------
def test_streamed_stats_equals_span_view(tmp_path):
    """Smoke run of the streamed flagship (CPU): the returned ``stats``
    timing keys must be exactly reproducible from the exported global
    snapshot via streamed_stats_view — the dict IS the view."""
    from adam_tpu.pipelines.streamed import transform_streamed
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 2048, 100)
    tele.TRACE.reset()
    tele.TRACE.recording = True
    try:
        stats = transform_streamed(
            path, str(tmp_path / "out.adam"), window_reads=512
        )
    finally:
        tele.TRACE.recording = False
    snap = tele.TRACE.snapshot()
    view = tele.streamed_stats_view(snap)
    assert view, "span view is empty — stage spans were not recorded"
    for key, want in view.items():
        assert stats[key] == want, key
    # every stage wall the old hand-maintained dict carried is present
    for key in ("ingest_pass_s", "resolve_s", "split_s", "observe_s",
                "solve_s", "realign_s", "apply_split_s", "write_wait_s",
                "total_s"):
        assert key in view, key
    # counters sanity: every read and window accounted for
    assert snap["counters"][tele.C_READS_INGESTED] == 2048
    assert snap["counters"][tele.C_WINDOWS_INGESTED] == 4
    assert snap["counters"][tele.C_PARTS_WRITTEN] >= 1
    assert snap["counters"][tele.C_BYTES_WRITTEN] > 0
    # the writer pool's submit-gate gauge saw real depth samples
    assert snap["gauges"][tele.G_POOL_DEPTH]["n"] >= 2
    assert snap["gauges"][tele.G_POOL_DEPTH]["max"] >= 1
    # per-window tokenize spans landed on the ingest thread's track
    tok = snap["spans"].get(tele.SPAN_TOKENIZE)
    assert tok and tok["count"] >= 4


def test_cli_metrics_json_and_trace_out(tmp_path, capsys):
    """Acceptance: transform with -print_metrics --metrics-json
    --trace-out yields a counters/gauges table under the timer table, a
    JSON snapshot whose per-stage walls match the printed rows, and a
    Chrome trace with overlapping stage spans on distinct tracks."""
    from adam_tpu.cli.main import main
    from make_synth_sam import make_sam

    sam = str(tmp_path / "in.sam")
    make_sam(sam, 1024, 100)
    mj = str(tmp_path / "m.json")
    to = str(tmp_path / "t.json")
    rc = main([
        "transform", sam, str(tmp_path / "out.adam"), "-streaming",
        "-mark_duplicate_reads", "-print_metrics",
        "--metrics-json", mj, "--trace-out", to,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Timings" in out and "Counters" in out
    doc = json.load(open(mj))
    # the printed timer rows and the JSON timers section are the same
    # data: every printed (name, count, total) reappears in the JSON
    lines = out.splitlines()
    start = lines.index("=======") + 2  # skip header row
    n_rows = 0
    for line in lines[start:]:
        if not line.strip():
            break
        m = re.fullmatch(r"(.+?)\s+(\d+)\s+(\d+\.\d{3})", line)
        assert m, line
        name, count, total = m.groups()
        row = doc["timers"][name]
        assert row["count"] == int(count)
        assert round(row["total_s"], 3) == float(total)
        n_rows += 1
    assert n_rows >= 3
    assert doc["counters"][tele.C_READS_INGESTED] == 1024
    # the Chrome trace is loadable and shows the overlap: ingest-thread
    # tokenize spans and main-thread stage spans on distinct tracks
    trace = json.load(open(to))
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert tele.SPAN_TOKENIZE in names and tele.SPAN_PASS_A in names
    tok_tids = {e["tid"] for e in spans if e["name"] == tele.SPAN_TOKENIZE}
    ing_tids = {e["tid"] for e in spans if e["name"] == tele.SPAN_PASS_A}
    assert tok_tids and ing_tids and not (tok_tids & ing_tids)
