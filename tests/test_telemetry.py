"""Telemetry layer: spans, counters, gauges, flight recorder, exports.

The contract under test (utils/telemetry.py, docs/OBSERVABILITY.md):
recording is lossless under concurrent writers (the ``TimerRegistry``
lock discipline), the flight recorder is bounded and evicts oldest
first, the JSON / Chrome-trace exports round-trip, and the streamed
pipeline's ``stats`` dict is a pure derived view of its span data —
recomputing the view from an exported snapshot reproduces it exactly.
"""

import json
import os
import re
import sys
import threading

import pytest

from adam_tpu.utils import instrumentation as ins
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


@pytest.fixture(autouse=True)
def _clean_global_state():
    """Tests toggle the process-wide TRACE/TIMERS; leave them as found."""
    rec_t, rec_i = tele.TRACE.recording, ins.TIMERS.recording
    yield
    tele.TRACE.recording = rec_t
    ins.TIMERS.recording = rec_i
    tele.TRACE.reset()
    ins.TIMERS.recording = True
    ins.TIMERS.reset()
    ins.TIMERS.recording = rec_i


# --------------------------------------------------------------------------
# core recorder
# --------------------------------------------------------------------------
def test_disabled_tracer_records_nothing():
    tr = tele.Tracer(recording=False)
    with tr.span(tele.SPAN_TOKENIZE, window=0):
        pass
    tr.count(tele.C_READS_INGESTED, 100)
    tr.gauge(tele.G_POOL_DEPTH, 3)
    snap = tr.snapshot()
    assert snap["spans"] == {} and snap["counters"] == {}
    assert snap["gauges"] == {} and snap["events_recorded"] == 0
    # the disabled fast path hands back one shared no-op object
    assert tr.span(tele.SPAN_SOLVE) is tr.span(tele.SPAN_TOKENIZE)


def test_concurrent_recording_is_lossless():
    """≥4 threads hammering spans+counters+gauges: nothing lost."""
    tr = tele.Tracer(recording=True, capacity=1 << 16)
    n_threads, per_thread = 8, 500
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(per_thread):
            with tr.span(tele.SPAN_TOKENIZE, window=i):
                pass
            tr.count(tele.C_READS_INGESTED, 2)
            tr.gauge(tele.G_POOL_DEPTH, tid)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    # concurrent readers must not race the writers (satellite: locked
    # snapshot) — exercise while recording is in flight
    for _ in range(50):
        tr.snapshot()
        tr.span_seconds()
    for t in threads:
        t.join()
    snap = tr.snapshot()
    total = n_threads * per_thread
    assert snap["spans"][tele.SPAN_TOKENIZE]["count"] == total
    assert snap["counters"][tele.C_READS_INGESTED] == 2 * total
    assert snap["gauges"][tele.G_POOL_DEPTH]["n"] == total
    assert snap["gauges"][tele.G_POOL_DEPTH]["min"] == 0
    assert snap["gauges"][tele.G_POOL_DEPTH]["max"] == n_threads - 1
    assert snap["events_recorded"] == total
    assert snap["events_evicted"] == 0


def test_ring_buffer_evicts_oldest_keeps_newest():
    tr = tele.Tracer(recording=True, capacity=16)
    t0 = 1_000_000
    for i in range(100):
        tr.add_span(tele.SPAN_TOKENIZE, t0 + i, 10, window=i)
    evs = tr.events()
    assert len(evs) == 16
    # newest 16 survive, oldest first within the ring
    assert [e["args"]["window"] for e in evs] == list(range(84, 100))
    snap = tr.snapshot()
    assert snap["events_recorded"] == 100
    assert snap["events_retained"] == 16
    assert snap["events_evicted"] == 84
    # aggregates live OUTSIDE the ring: totals stay exact post-eviction
    assert snap["spans"][tele.SPAN_TOKENIZE]["count"] == 100


def test_span_nesting_records_parent_and_thread():
    tr = tele.Tracer(recording=True)
    with tr.span(tele.SPAN_PASS_C):
        with tr.span(tele.SPAN_APPLY_DISPATCH, window=3):
            pass
    evs = tr.events()
    # inner exits (and records) first
    assert [e["name"] for e in evs] == [
        tele.SPAN_APPLY_DISPATCH, tele.SPAN_PASS_C,
    ]
    assert evs[0]["parent"] == tele.SPAN_PASS_C
    assert evs[0]["args"]["window"] == 3
    assert "parent" not in evs[1]
    assert evs[0]["thread"] == threading.current_thread().name


def test_absorb_merges_aggregates_and_events():
    a = tele.Tracer(recording=True)
    b = tele.Tracer(recording=True)
    for tr, k in ((a, 1), (b, 2)):
        for _ in range(k):
            with tr.span(tele.SPAN_SOLVE):
                pass
        tr.count(tele.C_PARTS_WRITTEN, k)
        tr.gauge(tele.G_DEVICE_INFLIGHT, k)
    a.absorb(b)
    snap = a.snapshot()
    assert snap["spans"][tele.SPAN_SOLVE]["count"] == 3
    assert snap["counters"][tele.C_PARTS_WRITTEN] == 3
    g = snap["gauges"][tele.G_DEVICE_INFLIGHT]
    assert (g["min"], g["max"], g["n"], g["last"]) == (1, 2, 2, 2)
    assert snap["events_recorded"] == 3


# --------------------------------------------------------------------------
# exports round-trip
# --------------------------------------------------------------------------
def _populated_tracer():
    tr = tele.Tracer(recording=True)
    with tr.span(tele.SPAN_PASS_A):
        with tr.span(tele.SPAN_TOKENIZE, window=0):
            pass
    tr.count(tele.C_WINDOWS_INGESTED)
    tr.gauge(tele.G_POOL_DEPTH, 2)
    return tr


def test_json_export_round_trips(tmp_path):
    tr = _populated_tracer()
    ins.TIMERS.recording = True
    ins.TIMERS.reset()
    ins.TIMERS.add(ins.SAM_ENCODE, 2_000_000_000)
    p = str(tmp_path / "m.json")
    tr.dump_json(p, include_events=True)
    doc = json.load(open(p))
    assert doc["meta"]["schema"] == "adam_tpu.telemetry/1"
    # the snapshot sections survive the file round-trip verbatim
    snap = tr.snapshot()
    assert doc["spans"] == snap["spans"]
    assert doc["counters"] == snap["counters"]
    assert doc["gauges"] == snap["gauges"]
    # the timers section is the TimerRegistry snapshot, same rows the
    # printed table carries
    assert doc["timers"][ins.SAM_ENCODE] == {"count": 1, "total_s": 2.0}
    # include_events carries the flight recorder
    assert [e["name"] for e in doc["events"]] == [
        e["name"] for e in tr.events()
    ]


def test_chrome_trace_export_loads_and_tracks_threads(tmp_path):
    tr = _populated_tracer()

    def other_thread():
        with tr.span(tele.SPAN_PART_ENCODE, rows=8):
            pass

    t = threading.Thread(target=other_thread, name="pw-enc-0")
    t.start()
    t.join()
    p = str(tmp_path / "t.json")
    tr.dump_chrome_trace(p)
    doc = json.load(open(p))
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    # one thread_name metadata record per recording thread, distinct tids
    names = {e["args"]["name"] for e in meta}
    assert "pw-enc-0" in names and len(names) == 2
    assert len({e["tid"] for e in meta}) == 2
    # complete events carry microsecond ts/dur on the right track
    by_name = {e["name"]: e for e in spans}
    assert set(by_name) == {
        tele.SPAN_PASS_A, tele.SPAN_TOKENIZE, tele.SPAN_PART_ENCODE,
    }
    ring = {e["name"]: e for e in tr.events()}
    for name, ev in by_name.items():
        assert ev["dur"] == pytest.approx(ring[name]["dur_ns"] / 1e3)
    enc_tid = by_name[tele.SPAN_PART_ENCODE]["tid"]
    tok_tid = by_name[tele.SPAN_TOKENIZE]["tid"]
    assert enc_tid != tok_tid
    # nesting attribution survives as args.parent
    assert by_name[tele.SPAN_TOKENIZE]["args"]["parent"] == tele.SPAN_PASS_A


def test_key_stable_snapshot_zero_fills_device_metrics():
    tr = tele.Tracer(recording=True)
    tr.count(tele.C_READS_INGESTED, 5)
    snap = tele.key_stable_snapshot(tr)
    for name in tele.DEVICE_ONLY_COUNTERS:
        assert snap["counters"][name] == 0
    for name in tele.DEVICE_ONLY_GAUGES:
        assert snap["gauges"][name] == {
            "last": 0, "min": 0, "max": 0, "n": 0,
        }
    # real values are never clobbered by the zero-fill
    assert snap["counters"][tele.C_READS_INGESTED] == 5


def test_merge_snapshots_reports_per_host_skew():
    def host(total_s):
        tr = tele.Tracer(recording=True)
        tr.add_span(tele.SPAN_PASS_A, 0, int(total_s * 1e9))
        return tr.snapshot()

    merged = tele.merge_snapshots([host(1.0), host(3.0)])
    assert merged["n_hosts"] == 2
    sk = merged["span_skew"][tele.SPAN_PASS_A]
    assert sk["min_s"] == pytest.approx(1.0)
    assert sk["max_s"] == pytest.approx(3.0)


# --------------------------------------------------------------------------
# TimerRegistry satellites
# --------------------------------------------------------------------------
def test_timer_snapshot_safe_during_recording():
    reg = ins.TimerRegistry(recording=True)
    stop = threading.Event()

    def hammer(i):
        while not stop.is_set():
            with reg.time(f"t{i}"):
                pass

    threads = [
        threading.Thread(target=hammer, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            snap = reg.snapshot()
            for name, (count, total_ns) in snap.items():
                assert count >= 1 and total_ns >= 0
            reg.report()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert set(reg.snapshot()) == {f"t{i}" for i in range(4)}


def test_timers_reset_clears_telemetry_metrics():
    ins.TIMERS.recording = True
    tele.TRACE.recording = True
    ins.TIMERS.add(ins.SAM_ENCODE, 1000)
    tele.TRACE.count(tele.C_PARTS_WRITTEN, 7)
    tele.TRACE.gauge(tele.G_POOL_DEPTH, 4)
    ins.TIMERS.reset()
    assert ins.TIMERS.snapshot() == {}
    snap = tele.TRACE.snapshot()
    # one reset clears the whole metrics surface (satellite 1)
    assert snap["counters"] == {} and snap["gauges"] == {}


def test_private_registry_reset_leaves_global_telemetry_alone():
    tele.TRACE.recording = True
    tele.TRACE.count(tele.C_PARTS_WRITTEN, 3)
    reg = ins.TimerRegistry(recording=True)
    reg.add(ins.SAM_ENCODE, 1000)
    reg.reset()
    assert reg.snapshot() == {}
    # only the process-global TIMERS reset cascades into TRACE
    assert tele.TRACE.snapshot()["counters"][tele.C_PARTS_WRITTEN] == 3


def test_device_trace_reentrant_noop(tmp_path, caplog, monkeypatch):
    """A second concurrent device_trace warns and no-ops instead of
    crashing the profiler (satellite 2)."""
    monkeypatch.setattr(ins, "_DEVICE_TRACE_ACTIVE", True)
    with caplog.at_level("WARNING", logger="adam_tpu.utils.instrumentation"):
        with ins.device_trace(str(tmp_path / "xprof")):
            pass
    assert any("already active" in r.message for r in caplog.records)
    # the no-op inner exit must NOT release the outer trace's guard
    assert ins._DEVICE_TRACE_ACTIVE is True


# --------------------------------------------------------------------------
# streamed pipeline: stats is a derived view of the span data
# --------------------------------------------------------------------------
def test_streamed_stats_equals_span_view(tmp_path):
    """Smoke run of the streamed flagship (CPU): the returned ``stats``
    timing keys must be exactly reproducible from the exported global
    snapshot via streamed_stats_view — the dict IS the view."""
    from adam_tpu.pipelines.streamed import transform_streamed
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 2048, 100)
    tele.TRACE.reset()
    tele.TRACE.recording = True
    try:
        stats = transform_streamed(
            path, str(tmp_path / "out.adam"), window_reads=512
        )
    finally:
        tele.TRACE.recording = False
    snap = tele.TRACE.snapshot()
    view = tele.streamed_stats_view(snap)
    assert view, "span view is empty — stage spans were not recorded"
    for key, want in view.items():
        assert stats[key] == want, key
    # every stage wall the old hand-maintained dict carried is present
    for key in ("ingest_pass_s", "resolve_s", "split_s", "observe_s",
                "solve_s", "realign_s", "apply_split_s", "write_wait_s",
                "total_s"):
        assert key in view, key
    # counters sanity: every read and window accounted for
    assert snap["counters"][tele.C_READS_INGESTED] == 2048
    assert snap["counters"][tele.C_WINDOWS_INGESTED] == 4
    assert snap["counters"][tele.C_PARTS_WRITTEN] >= 1
    assert snap["counters"][tele.C_BYTES_WRITTEN] > 0
    # the writer pool's submit-gate gauge saw real depth samples
    assert snap["gauges"][tele.G_POOL_DEPTH]["n"] >= 2
    assert snap["gauges"][tele.G_POOL_DEPTH]["max"] >= 1
    # per-window tokenize spans landed on the ingest thread's track
    tok = snap["spans"].get(tele.SPAN_TOKENIZE)
    assert tok and tok["count"] >= 4


def test_cli_metrics_json_and_trace_out(tmp_path, capsys):
    """Acceptance: transform with -print_metrics --metrics-json
    --trace-out yields a counters/gauges table under the timer table, a
    JSON snapshot whose per-stage walls match the printed rows, and a
    Chrome trace with overlapping stage spans on distinct tracks."""
    from adam_tpu.cli.main import main
    from make_synth_sam import make_sam

    sam = str(tmp_path / "in.sam")
    make_sam(sam, 1024, 100)
    mj = str(tmp_path / "m.json")
    to = str(tmp_path / "t.json")
    rc = main([
        "transform", sam, str(tmp_path / "out.adam"), "-streaming",
        "-mark_duplicate_reads", "-print_metrics",
        "--metrics-json", mj, "--trace-out", to,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Timings" in out and "Counters" in out
    doc = json.load(open(mj))
    # the printed timer rows and the JSON timers section are the same
    # data: every printed (name, count, total) reappears in the JSON
    lines = out.splitlines()
    start = lines.index("=======") + 2  # skip header row
    n_rows = 0
    for line in lines[start:]:
        if not line.strip():
            break
        m = re.fullmatch(r"(.+?)\s+(\d+)\s+(\d+\.\d{3})", line)
        assert m, line
        name, count, total = m.groups()
        row = doc["timers"][name]
        assert row["count"] == int(count)
        assert round(row["total_s"], 3) == float(total)
        n_rows += 1
    assert n_rows >= 3
    assert doc["counters"][tele.C_READS_INGESTED] == 1024
    # the Chrome trace is loadable and shows the overlap: ingest-thread
    # tokenize spans and main-thread stage spans on distinct tracks
    trace = json.load(open(to))
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert tele.SPAN_TOKENIZE in names and tele.SPAN_PASS_A in names
    tok_tids = {e["tid"] for e in spans if e["name"] == tele.SPAN_TOKENIZE}
    ing_tids = {e["tid"] for e in spans if e["name"] == tele.SPAN_PASS_A}
    assert tok_tids and ing_tids and not (tok_tids & ing_tids)


# --------------------------------------------------------------------------
# histograms: fixed log-spaced buckets, quantiles, merges
# --------------------------------------------------------------------------
def test_histogram_bucket_edges_are_fixed_and_log_spaced():
    """Bucket i spans [10^(i/4), 10^((i+1)/4)) — global, data-independent
    edges, and every observed value lands in exactly its bucket."""
    for v in (1e-6, 0.001, 0.5, 1.0, 3.7, 42.0, 1e4):
        idx = tele.hist_bucket_index(v)
        lo, hi = tele.hist_bucket_bounds(idx)
        assert lo <= v < hi, (v, idx, lo, hi)
    # adjacent buckets tile the line with ratio 10^(1/4)
    lo0, hi0 = tele.hist_bucket_bounds(0)
    lo1, hi1 = tele.hist_bucket_bounds(1)
    assert hi0 == pytest.approx(lo1)
    assert hi0 / lo0 == pytest.approx(10 ** 0.25)
    # nonpositive values clamp into the lowest bucket instead of NaN-ing
    assert tele.hist_bucket_index(0.0) == tele.hist_bucket_index(-5.0)


def test_histogram_observe_and_quantiles():
    tr = tele.Tracer(recording=True)
    for v in [0.001] * 90 + [1.0] * 9 + [10.0]:
        tr.observe(tele.H_FETCH_SECONDS, v)
    h = tr.snapshot()["histograms"][tele.H_FETCH_SECONDS]
    assert h["count"] == 100
    assert h["min"] == 0.001 and h["max"] == 10.0
    assert h["sum"] == pytest.approx(0.09 + 9.0 + 10.0)
    # p50 sits in the 1ms bucket, p99 in the 1s bucket (bucket-midpoint
    # estimates: within one bucket ratio of the true value)
    assert h["p50"] == pytest.approx(0.001, rel=1.0)
    assert 0.5 <= h["p99"] <= 2.0
    # the max observation is only reachable at the very top quantile
    assert h["p99"] < h["max"]


def test_histogram_merge_is_associative():
    def hist(values):
        tr = tele.Tracer(recording=True)
        for v in values:
            tr.observe(tele.H_FETCH_SECONDS, v)
        return tr.snapshot()["histograms"][tele.H_FETCH_SECONDS]

    a = hist([0.001, 0.002, 0.004])
    b = hist([1.0, 2.0])
    c = hist([50.0, 0.0005])
    left = tele.merge_histograms(tele.merge_histograms(a, b), c)
    right = tele.merge_histograms(a, tele.merge_histograms(b, c))
    assert left == right
    assert left["count"] == 7
    assert left["min"] == 0.0005 and left["max"] == 50.0
    # merging with an empty histogram is the identity
    assert tele.merge_histograms(a, {}) == tele.merge_histograms({}, a)


def test_observe_concurrent_is_lossless():
    """≥8 threads hammering observe(): nothing lost, bounds exact."""
    tr = tele.Tracer(recording=True)
    n_threads, per_thread = 8, 400
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(per_thread):
            tr.observe(tele.H_POOL_SUBMIT_WAIT, 0.001 * (tid + 1))

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for _ in range(50):  # concurrent readers must not race the writers
        tr.snapshot()
    for t in threads:
        t.join()
    h = tr.snapshot()["histograms"][tele.H_POOL_SUBMIT_WAIT]
    assert h["count"] == n_threads * per_thread
    assert h["min"] == pytest.approx(0.001)
    assert h["max"] == pytest.approx(0.008)
    assert sum(h["buckets"].values()) == h["count"]


def test_spans_get_automatic_duration_histograms():
    tr = tele.Tracer(recording=True)
    tr.add_span(tele.SPAN_SOLVE, 0, int(0.25 * 1e9))
    tr.add_span(tele.SPAN_SOLVE, 0, int(0.5 * 1e9))
    snap = tr.snapshot()
    h = snap["histograms"][tele.SPAN_SOLVE]
    assert h["count"] == snap["spans"][tele.SPAN_SOLVE]["count"] == 2
    assert h["min"] == pytest.approx(0.25)
    assert h["max"] == pytest.approx(0.5)
    # disabled tracers record no histograms at all
    off = tele.Tracer(recording=False)
    off.observe(tele.H_FETCH_SECONDS, 1.0)
    assert off.snapshot()["histograms"] == {}


def test_absorb_and_merge_snapshots_carry_histograms():
    a = tele.Tracer(recording=True)
    b = tele.Tracer(recording=True)
    a.observe(tele.H_FETCH_SECONDS, 0.1)
    b.observe(tele.H_FETCH_SECONDS, 10.0)
    sa, sb = a.snapshot(), b.snapshot()
    a.absorb(b)
    h = a.snapshot()["histograms"][tele.H_FETCH_SECONDS]
    assert h["count"] == 2 and h["min"] == 0.1 and h["max"] == 10.0
    merged = tele.merge_snapshots([sa, sb])
    mh = merged["histograms"][tele.H_FETCH_SECONDS]
    assert mh["count"] == 2 and mh["min"] == 0.1 and mh["max"] == 10.0


def test_key_stable_snapshot_zero_fills_histograms():
    tr = tele.Tracer(recording=True)
    snap = tele.key_stable_snapshot(tr)
    for name in tele.DEVICE_ONLY_HISTOGRAMS:
        h = snap["histograms"][name]
        assert h["count"] == 0 and h["p50"] is None


def test_report_prints_histogram_quantiles():
    tr = tele.Tracer(recording=True)
    tr.observe(tele.H_FETCH_SECONDS, 0.5)
    text = tr.report()
    assert "Histograms" in text
    assert tele.H_FETCH_SECONDS in text


# --------------------------------------------------------------------------
# device_spans: replayed work never conflates with organic occupancy
# --------------------------------------------------------------------------
def test_device_spans_separate_replayed_from_organic_work():
    """The eviction-attribution fix: an evicted device's pre-eviction
    spans stay under its original key, and the windows a survivor
    re-runs for it aggregate under `<survivor>:replay` — never summed
    into the survivor's own row."""
    tr = tele.Tracer(recording=True)
    # pre-eviction: devices 0 and 1 each do organic work
    tr.add_span(tele.SPAN_APPLY_DISPATCH, 0, int(1e9), device=0)
    tr.add_span(tele.SPAN_APPLY_DISPATCH, 0, int(2e9), device=1)
    # device 1 dies; its window replays on device 0 with the replay attr
    tr.add_span(tele.SPAN_POOL_REPLAY, 0, int(3e9), device=1)
    tr.add_span(tele.SPAN_APPLY_DISPATCH, 0, int(4e9), device=0, replay=1)
    dev = tr.snapshot()["device_spans"]
    disp = dev[tele.SPAN_APPLY_DISPATCH]
    # organic rows untouched by the replay
    assert disp["0"] == {"count": 1, "total_s": pytest.approx(1.0)}
    assert disp["1"] == {"count": 1, "total_s": pytest.approx(2.0)}
    # replayed work lands under the survivor's :replay key
    assert disp["0:replay"] == {"count": 1, "total_s": pytest.approx(4.0)}
    # the umbrella stays attributed to the FAILED chip
    assert dev[tele.SPAN_POOL_REPLAY]["1"]["total_s"] == pytest.approx(3.0)
    # cascading eviction: a device dying MID-replay records its own
    # umbrella inside the outer replay scope (replay=1 attr), which is
    # exempt from the :replay rewrite — recovery wall must stay under
    # the failed chip's plain key or the analyzer counts it as busy
    # and misses the eviction
    tr.add_span(tele.SPAN_POOL_REPLAY, 0, int(1e9), device=0, replay=1)
    dev2 = tr.snapshot()["device_spans"][tele.SPAN_POOL_REPLAY]
    assert "0:replay" not in dev2
    assert dev2["0"]["total_s"] == pytest.approx(1.0)


# --------------------------------------------------------------------------
# heartbeat
# --------------------------------------------------------------------------
def test_progress_sink_from_env(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_PROGRESS", raising=False)
    assert tele.progress_sink_from_env() is None
    monkeypatch.setenv("ADAM_TPU_PROGRESS", "0")
    assert tele.progress_sink_from_env() is None
    for raw in ("1", "stderr", "-"):
        monkeypatch.setenv("ADAM_TPU_PROGRESS", raw)
        assert tele.progress_sink_from_env() == "stderr"
    monkeypatch.setenv("ADAM_TPU_PROGRESS", "/tmp/hb.ndjson")
    assert tele.progress_sink_from_env() == "/tmp/hb.ndjson"
    monkeypatch.setenv("ADAM_TPU_PROGRESS_INTERVAL_S", "bogus")
    assert tele.progress_interval_s() == pytest.approx(2.0)
    monkeypatch.setenv("ADAM_TPU_PROGRESS_INTERVAL_S", "0.25")
    assert tele.progress_interval_s() == pytest.approx(0.25)


def test_heartbeat_ndjson_schema_is_stable(tmp_path):
    """Every emitted line carries exactly HEARTBEAT_FIELDS, in order;
    the final line is done=true; counters sum across the sampled
    tracers (run tracer + global TRACE, as the streamed wiring does)."""
    tr = tele.Tracer(recording=True)
    other = tele.Tracer(recording=True)
    tr.count(tele.C_WINDOWS_INGESTED, 3)
    tr.count(tele.C_READS_INGESTED, 3000)
    tr.count(tele.C_RESUME_WINDOWS_SKIPPED, 2)
    other.count(tele.C_PARTS_WRITTEN, 2)
    other.count(tele.C_BYTES_WRITTEN, 12345)
    p = str(tmp_path / "hb.ndjson")
    hb = tele.Heartbeat([tr, other], sink=p, interval_s=0.05)
    hb.set_total(4)
    hb.set_provider(lambda: {"inflight_per_device": {"0": 2, "1": 1}})
    hb.start()
    import time as _time

    _time.sleep(0.2)
    hb.stop()
    hb.stop()  # idempotent
    lines = [json.loads(l) for l in open(p)]
    assert len(lines) >= 3  # start line + >=1 periodic + final
    for l in lines:
        assert tuple(l.keys()) == tele.HEARTBEAT_FIELDS
        assert l["schema"] == tele.HEARTBEAT_SCHEMA
    last = lines[-1]
    assert last["done"] is True
    assert last["windows_ingested"] == 3
    assert last["reads_ingested"] == 3000
    assert last["parts_written"] == 2
    assert last["bytes_written"] == 12345
    assert last["windows_total"] == 4
    # resumed-vs-fresh visibility: the resume.windows_skipped counter
    # surfaces as the windows_resumed field (0 on fresh runs)
    assert last["windows_resumed"] == 2
    assert last["inflight_per_device"] == {"0": 2, "1": 1}
    assert last["eta_s"] is not None  # 2 of 4 parts -> extrapolable
    assert [l["seq"] for l in lines] == list(range(len(lines)))
    # a broken provider must not kill the beat
    hb2 = tele.Heartbeat([tr], sink=str(tmp_path / "hb2.ndjson"),
                         interval_s=5.0)
    hb2.set_provider(lambda: 1 / 0)
    hb2.start()
    hb2.stop()
    assert len(open(str(tmp_path / "hb2.ndjson")).readlines()) == 2
    # a crashed run's final line carries ok=false (the streamed
    # wrapper's exception path calls stop(ok=False)): done alone must
    # never read as success
    hb3 = tele.Heartbeat([tr], sink=str(tmp_path / "hb3.ndjson"),
                         interval_s=5.0)
    hb3.start()
    hb3.stop(ok=False)
    crash_lines = [json.loads(l) for l in open(str(tmp_path / "hb3.ndjson"))]
    assert crash_lines[0]["ok"] is True
    assert crash_lines[-1] == {**crash_lines[-1], "done": True, "ok": False}


def test_heartbeat_disabled_is_a_noop(tmp_path, monkeypatch):
    """No sink configured => the streamed pipeline constructs no
    heartbeat, flips no global state, and emits nothing."""
    from adam_tpu.pipelines import streamed as st

    monkeypatch.delenv("ADAM_TPU_PROGRESS", raising=False)
    tele.TRACE.recording = False  # fixture restores the entry value
    tr = tele.Tracer(recording=True)
    assert st._start_heartbeat(tr, None) is None
    assert tele.TRACE.recording is False
    st._stop_heartbeat(None)  # no-op on the disabled path
    # with a sink, global recording flips on for the heartbeat's
    # lifetime and is restored on stop — along with the recorded state,
    # so back-to-back runs cannot sum counters into each other's beats
    hb = st._start_heartbeat(tr, str(tmp_path / "hb.ndjson"))
    assert hb is not None and tele.TRACE.recording is True
    tele.TRACE.count(tele.C_PARTS_WRITTEN, 3)  # a mid-run parquet count
    st._stop_heartbeat(hb)
    assert tele.TRACE.recording is False
    assert tele.TRACE.snapshot()["counters"] == {}


# --------------------------------------------------------------------------
# device ledger: transfer accounting / compile ledger / HBM
# --------------------------------------------------------------------------
def test_transfer_ledger_attributes_per_device_and_pass():
    """record_transfer lands the byte counters, the per-direction
    throughput histograms, and a transfers section keyed by device and
    by the thread-local pass_scope; absorb() merges all of it."""
    tr = tele.Tracer(recording=True)
    with tele.pass_scope("a"):
        tr.record_transfer("h2d", 1000, 0.001, device="0")
        with tele.pass_scope("observe"):  # inner scope shadows outer
            tr.record_transfer("d2h", 4000, 0.002, device="0")
    tr.record_transfer("d2h", 500, 0.0, device="1")  # no wall -> no bps
    snap = tr.snapshot()
    assert snap["counters"][tele.C_H2D_BYTES] == 1000
    assert snap["counters"][tele.C_D2H_BYTES] == 4500
    assert snap["transfers"]["h2d"]["0"]["a"]["bytes"] == 1000
    assert snap["transfers"]["d2h"]["0"]["observe"]["count"] == 1
    assert snap["transfers"]["d2h"]["1"][tele.PASS_OTHER]["bytes"] == 500
    # throughput histograms: 1 MB/s and 2 MB/s observed; the zero-wall
    # transfer contributed bytes but no bps sample
    assert snap["histograms"][tele.H_H2D_BPS]["count"] == 1
    assert snap["histograms"][tele.H_D2H_BPS]["count"] == 1
    assert snap["histograms"][tele.H_D2H_BPS]["max"] == pytest.approx(2e6)
    # disabled tracer records nothing
    off = tele.Tracer(recording=False)
    off.record_transfer("h2d", 10, 0.1, device="0")
    assert off.snapshot()["transfers"] == {}
    # absorb merges the per-(device, pass) aggregates additively
    dst = tele.Tracer(recording=True)
    dst.record_transfer("h2d", 24, 0.001, device="0", pass_name="a")
    dst.absorb(tr)
    merged = dst.snapshot()["transfers"]
    assert merged["h2d"]["0"]["a"] == {
        "count": 2, "bytes": 1024, "seconds": pytest.approx(0.002),
    }


def test_compile_ledger_hit_miss_and_in_window_flag():
    """First dispatch of a (kernel, shape, device) triple is a miss
    (flagged in_window outside a prewarm scope), later dispatches are
    hits; a raising dispatch gives its claim back for the retry."""
    from adam_tpu.utils import compile_ledger as cl

    cl.reset()
    tele.TRACE.recording = True
    tele.TRACE.reset()
    key = ("test.kernel", 128, 64)
    with cl.prewarm_scope():
        with cl.track(key, None):
            pass  # "compile" under prewarm
    with cl.track(key, None):
        pass  # warm now -> hit
    with cl.track(("test.kernel", 256, 64), None):
        pass  # new shape at a dispatch site -> in-window miss
    with pytest.raises(RuntimeError):
        with cl.track(("test.kernel", 512, 64), None):
            raise RuntimeError("transient")
    with cl.track(("test.kernel", 512, 64), None):
        pass  # the discarded claim makes the retry a (recorded) miss
    snap = tele.TRACE.snapshot()
    assert snap["counters"][tele.C_COMPILE_MISSES] == 3
    assert snap["counters"][tele.C_COMPILE_HITS] == 1
    assert snap["counters"][tele.C_COMPILE_IN_WINDOW] == 2
    entries = snap["compiles"]["entries"]
    assert [e["in_window"] for e in entries] == [False, True, True]
    assert entries[0]["kernel"] == "test.kernel"
    assert entries[0]["shape"] == [128, 64]
    assert entries[0]["device"] == "default"
    assert snap["histograms"][tele.H_COMPILE_SECONDS]["count"] == 3
    cl.reset()


def test_hbm_ledger_tracks_peak_and_key_stability():
    tr = tele.Tracer(recording=True)
    tr.record_hbm("0", 1000, peak_bytes=1500)
    tr.record_hbm("0", 800)
    tr.record_hbm("1", 2000)
    snap = tr.snapshot()
    assert snap["hbm"]["0"] == {"last": 800, "peak": 1500, "n": 2}
    assert snap["hbm"]["1"] == {"last": 2000, "peak": 2000, "n": 1}
    # absorb keeps the max peak
    dst = tele.Tracer(recording=True)
    dst.record_hbm("0", 3000)
    dst.absorb(tr)
    assert dst.snapshot()["hbm"]["0"] == {"last": 800, "peak": 3000, "n": 3}
    # the CPU bench leg zero-fills the ledger sections key-stably
    ks = tele.key_stable_snapshot(tele.Tracer(recording=True))
    assert ks["transfers"] == {"h2d": {}, "d2h": {}}
    assert ks["compiles"] == {"entries": [], "dropped": 0}
    assert ks["hbm"] == {}
    for name in (tele.C_H2D_BYTES, tele.C_D2H_BYTES,
                 tele.C_COMPILE_HITS, tele.C_COMPILE_MISSES):
        assert ks["counters"][name] == 0
    for name in (tele.H_H2D_BPS, tele.H_D2H_BPS, tele.H_COMPILE_SECONDS):
        assert ks["histograms"][name]["count"] == 0


def test_heartbeat_v2_carries_tunnel_and_hbm_fields(tmp_path):
    """The /2 schema fields: tunnel byte totals from the counters, HBM
    as {} + null on backends without memory stats (the explicit
    unsupported marker, distinct from zeros)."""
    tr = tele.Tracer(recording=True)
    tr.record_transfer("h2d", 12345, 0.001, device="0", pass_name="a")
    tr.record_transfer("d2h", 54321, 0.002, device="0", pass_name="apply")
    hb = tele.Heartbeat([tr], sink=str(tmp_path / "hb.ndjson"),
                        interval_s=5.0)
    hb.set_devices([])  # no devices -> unsupported marker path
    hb.start()
    hb.stop()
    lines = [json.loads(l) for l in open(str(tmp_path / "hb.ndjson"))]
    assert lines[-1]["schema"] == "adam_tpu.heartbeat/7"
    assert lines[-1]["h2d_bytes"] == 12345
    assert lines[-1]["d2h_bytes"] == 54321
    assert lines[-1]["hbm_bytes_in_use"] == {}
    assert lines[-1]["hbm_peak_bytes"] is None
    for l in lines:
        assert tuple(l.keys()) == tele.HEARTBEAT_FIELDS


def test_heartbeat_rotation_caps_file_size(tmp_path, monkeypatch):
    """Past ADAM_TPU_PROGRESS_MAX_BYTES the sink rotates to <path>.1
    and a fresh file continues — no line is lost or torn across the
    rotation, and seq stays monotonic across both files."""
    monkeypatch.setenv("ADAM_TPU_PROGRESS_MAX_BYTES", "600")
    tr = tele.Tracer(recording=True)
    p = str(tmp_path / "hb.ndjson")
    hb = tele.Heartbeat([tr], sink=p, interval_s=60.0)
    hb.set_devices([])
    hb.start()
    for _ in range(6):  # each line is a few hundred bytes
        hb._emit(done=False)
    hb.stop()
    rotated = p + ".1"
    assert os.path.exists(rotated), "no rotation happened"
    assert os.path.getsize(p) < 1200
    all_lines = []
    for path in (rotated, p):
        for raw in open(path):
            assert raw.endswith("\n")
            all_lines.append(json.loads(raw))
    # rotation keeps only the newest two files (bounded disk is the
    # point): the surviving seqs are contiguous and end at the final
    # line — nothing torn, nothing duplicated
    seqs = [l["seq"] for l in all_lines]
    assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
    assert all_lines[-1]["done"] is True
    # rotation happens BEFORE each write, so the final done=true line
    # is always in the LIVE file — a tailer (`adam-tpu top`) watching
    # the sink path must never have its exit line rotated away
    live = [json.loads(raw) for raw in open(p)]
    assert live and live[-1]["done"] is True
    monkeypatch.delenv("ADAM_TPU_PROGRESS_MAX_BYTES")
    assert tele.progress_max_bytes() == 64 * 1024 * 1024
    monkeypatch.setenv("ADAM_TPU_PROGRESS_MAX_BYTES", "bogus")
    assert tele.progress_max_bytes() == 64 * 1024 * 1024
    monkeypatch.setenv("ADAM_TPU_PROGRESS_MAX_BYTES", "0")
    assert tele.progress_max_bytes() == 0


# --------------------------------------------------------------------------
# trace context: job-scoped distributed traces (docs/OBSERVABILITY.md)
# --------------------------------------------------------------------------
def test_mint_trace_id_shape_and_uniqueness():
    ids = {tele.mint_trace_id() for _ in range(64)}
    assert len(ids) == 64
    for tid in ids:
        assert re.fullmatch(r"[0-9a-f]{16}", tid), tid


def test_trace_attribution_precedence():
    """Explicit span attr > thread-local trace_scope > tracer default;
    trace_scope(None) is a no-op frame that does NOT shadow an outer
    scope."""
    tr = tele.Tracer(recording=True)
    tr.set_trace("d" * 16)
    with tr.span(tele.SPAN_TOKENIZE, window=0):
        pass
    with tele.trace_scope("5" * 16):
        with tr.span(tele.SPAN_TOKENIZE, window=1):
            pass
        with tele.trace_scope(None):  # no-op frame
            with tr.span(tele.SPAN_TOKENIZE, window=2):
                pass
        with tr.span(tele.SPAN_TOKENIZE, window=3, trace="e" * 16):
            pass
    by_window = {e["args"]["window"]: e.get("trace")
                 for e in tr.events() if e.get("name") != "process_name"}
    assert by_window == {
        0: "d" * 16, 1: "5" * 16, 2: "5" * 16, 3: "e" * 16,
    }
    assert tele.current_trace() is None  # scopes unwound


def test_event_in_trace_matches_stamp_and_fanin_links():
    tid_a, tid_b = "a" * 16, "b" * 16
    tr = tele.Tracer(recording=True)
    with tr.span(tele.SPAN_APPLY_DISPATCH, window=0, trace=tid_a):
        pass
    # the fused cross-job dispatch claims NO single trace; it links
    # every contributing job's {job, window, trace} instead
    with tr.span(tele.SPAN_BATCH_FUSED, kind="markdup", links=[
        {"job": "j1", "window": 0, "trace": tid_a},
        {"job": "j2", "window": 3, "trace": tid_b},
    ]):
        pass
    ev_a = tr.events_for_trace(tid_a)
    ev_b = tr.events_for_trace(tid_b)
    names_a = {e["name"] for e in ev_a}
    assert tele.SPAN_APPLY_DISPATCH in names_a
    assert tele.SPAN_BATCH_FUSED in names_a
    # job B sees the SHARED fused span but not job A's private span
    assert {e["name"] for e in ev_b if e["name"] != "process_name"} \
        == {tele.SPAN_BATCH_FUSED}
    assert not tr.events_for_trace("c" * 16)


def test_per_trace_aggregates_survive_ring_eviction():
    tid = "f" * 16
    tr = tele.Tracer(recording=True, capacity=8)
    for i in range(64):
        with tr.span(tele.SPAN_TOKENIZE, window=i, trace=tid):
            pass
    assert len(tr.events_for_trace(tid)) <= 8  # ring evicted most
    agg = tr.snapshot()["traces"][tid]
    assert agg["events"] == 64  # ...but the ledger kept counting
    assert agg["total_s"] >= 0.0


def test_chrome_trace_export_filters_to_one_trace():
    tid_a, tid_b = "a" * 16, "b" * 16
    tr = tele.Tracer(recording=True)
    with tr.span(tele.SPAN_APPLY_DISPATCH, window=0, trace=tid_a):
        pass
    with tr.span(tele.SPAN_APPLY_FETCH, window=9, trace=tid_b):
        pass
    with tr.span(tele.SPAN_BATCH_FUSED, links=[
        {"job": "j1", "window": 0, "trace": tid_a},
    ]):
        pass
    doc = tr.to_chrome_trace(tid_a)
    names = {e["name"] for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    assert tele.SPAN_APPLY_DISPATCH in names
    assert tele.SPAN_BATCH_FUSED in names
    assert tele.SPAN_APPLY_FETCH not in names  # job B's private span
    # the per-trace ledger is filtered with the export
    assert set(doc["traces"]) == {tid_a}
    # the unfiltered export carries both jobs' aggregates
    assert set(tr.to_chrome_trace()["traces"]) == {tid_a, tid_b}


def test_absorb_carries_per_trace_aggregates():
    """A job-scoped run tracer folds into the global tracer without
    losing its trace ledger (the /trace surface reads the global)."""
    tid = "1" * 16
    run = tele.Tracer(recording=True)
    run.set_trace(tid)
    with run.span(tele.SPAN_TOKENIZE, window=0):
        pass
    glob = tele.Tracer(recording=True)
    glob.absorb(run)
    assert glob.snapshot()["traces"][tid]["events"] == 1
    assert len(glob.events_for_trace(tid)) == 1


def test_merge_snapshots_traces_associative():
    """Per-trace aggregates are plain sums: any grouping of host
    snapshots yields the same traces section (the satellite's
    associativity requirement)."""
    def host(tid_events):
        tr = tele.Tracer(recording=True)
        for tid, n in tid_events:
            for i in range(n):
                with tr.span(tele.SPAN_TOKENIZE, window=i, trace=tid):
                    pass
        return tr.snapshot()

    a = host([("a" * 16, 2)])
    b = host([("a" * 16, 3), ("b" * 16, 1)])
    c = host([("b" * 16, 5)])
    flat = tele.merge_snapshots([a, b, c])["traces"]
    left = tele.merge_snapshots(
        [tele.merge_snapshots([a, b]), c])["traces"]
    right = tele.merge_snapshots(
        [a, tele.merge_snapshots([b, c])])["traces"]
    for merged in (left, right):
        assert set(merged) == set(flat)
        for tid in flat:
            assert merged[tid]["events"] == flat[tid]["events"]
            assert merged[tid]["total_s"] == \
                pytest.approx(flat[tid]["total_s"])
    assert flat["a" * 16]["events"] == 5
    assert flat["b" * 16]["events"] == 6


def test_active_trace_registry_is_refcounted():
    tid = "9" * 16
    assert tid not in tele.active_traces()
    tele.activate_trace(tid)
    tele.activate_trace(tid)  # re-entrant (recovery re-runs)
    assert tid in tele.active_traces()
    tele.deactivate_trace(tid)
    assert tid in tele.active_traces()
    tele.deactivate_trace(tid)
    assert tid not in tele.active_traces()
    tele.activate_trace(None)  # no-op, never raises
    tele.deactivate_trace(None)


def test_prometheus_exposition_from_snapshot():
    """gateway/metrics.render_prometheus: valid exposition text off a
    live snapshot — counters, gauges, cumulative histogram buckets,
    and the trace gauges; every series name valid per the grammar."""
    from adam_tpu.gateway import metrics as gw_metrics

    tr = tele.Tracer(recording=True)
    tr.count(tele.C_READS_INGESTED, 7)
    tr.gauge(tele.G_POOL_DEPTH, 3)
    for v in (0.001, 0.01, 0.1):
        tr.observe(tele.H_FETCH_SECONDS, v)
    text = gw_metrics.render_prometheus(tr.snapshot())
    assert text.endswith("\n")
    assert "adam_tpu_reads_ingested 7" in text
    assert "adam_tpu_parquet_pool_queue_depth 3" in text
    assert "adam_tpu_device_fetch_seconds_count 3" in text
    assert 'le="+Inf"' in text
    assert "adam_tpu_traces_active" in text
    # grammar: every sample line's metric name is valid; buckets are
    # cumulative (non-decreasing per series)
    bucket_acc = {}
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name = line.split("{")[0].split(" ")[0]
        assert tele.prometheus_name_valid(name), line
        if "_bucket" in line:
            v = float(line.rsplit(" ", 1)[1])
            assert v >= bucket_acc.get(name, 0.0), line
            bucket_acc[name] = v
    # HELP/TYPE precede every series
    assert text.index("# TYPE adam_tpu_reads_ingested counter") \
        < text.index("adam_tpu_reads_ingested 7")


def test_prometheus_exposition_sanitizes_display_timer_names():
    """The 8 legacy display-style timer names ('BGZF Codec (native)')
    can reach a snapshot via span-duration auto-histograms; the
    renderer sanitizes them rather than emitting invalid series."""
    from adam_tpu.gateway import metrics as gw_metrics

    snap = {"counters": {}, "gauges": {},
            "histograms": {"BGZF Codec (native)": {
                "count": 1, "sum": 0.5, "min": 0.5, "max": 0.5,
                "buckets": {"0": 1},
            }}}
    text = gw_metrics.render_prometheus(snap)
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name = line.split("{")[0].split(" ")[0]
        assert tele.prometheus_name_valid(name), line


def test_heartbeat_v6_trace_and_incident_fields(tmp_path):
    """/6 appends active_traces / metrics_scrapes / last_incident /
    last_incident_age_s — populated from the live registries."""
    from adam_tpu.utils import incidents

    tid = "c" * 16
    tr = tele.Tracer(recording=True)
    tr.count(tele.C_GW_SCRAPES, 4)
    incidents.install(str(tmp_path))
    tele.activate_trace(tid)
    try:
        incidents.maybe_record("hedge.fired", tracer=tr,
                               reason="test bundle")
        p = str(tmp_path / "hb.ndjson")
        hb = tele.Heartbeat([tr], sink=p, interval_s=60.0)
        hb.set_devices([])
        hb.start()
        hb.stop()
        lines = [json.loads(raw) for raw in open(p)]
    finally:
        tele.deactivate_trace(tid)
        incidents.uninstall()
    line = lines[-1]
    assert line["schema"] == "adam_tpu.heartbeat/7"
    assert list(line) == list(tele.HEARTBEAT_FIELDS)
    assert line["active_traces"] >= 1
    assert line["metrics_scrapes"] == 4
    assert line["last_incident"].startswith("inc-")
    assert line["last_incident_age_s"] >= 0.0


def test_merge_snapshots_health_missing_side_key_stable():
    """A host snapshot without a health section (host-only worker, or
    an older artifact) merges key-stably: the present side passes
    through and the merged doc still carries the key."""
    row = {"state": "suspect", "score": 0.4,
           "reason": "slow fetch", "transitions": 2}
    with_health = {"spans": {}, "health": {"0": dict(row)}}
    without = {"spans": {}}  # no health key at all
    merged = tele.merge_snapshots([with_health, without])
    assert merged["health"] == {"0": row}
    # both orders, and an all-missing merge still carries the key
    assert tele.merge_snapshots([without, with_health])["health"] == \
        {"0": row}
    assert tele.merge_snapshots([without, without])["health"] == {}


def test_merge_snapshots_health_worst_state_wins():
    a = {"health": {"0": {"state": "healthy", "score": 0.9,
                          "reason": "", "transitions": 1}}}
    b = {"health": {"0": {"state": "evicted", "score": 0.1,
                          "reason": "SDC mismatch", "transitions": 3}}}
    for order in ([a, b], [b, a]):
        got = tele.merge_snapshots(order)["health"]["0"]
        assert got["state"] == "evicted"
        assert got["reason"] == "SDC mismatch"
        assert got["score"] == pytest.approx(0.1)
        assert got["transitions"] == 4


def test_merge_snapshots_quota_missing_side_key_stable():
    row = {"charges": 3, "bytes": 100, "compute_s": 1.5,
           "budget_bytes": 1000, "budget_compute_s": None}
    with_quota = {"quota": {"t1": dict(row)}}
    without = {"spans": {}}
    for order in ([with_quota, without], [without, with_quota]):
        assert tele.merge_snapshots(order)["quota"] == {"t1": row}
    assert tele.merge_snapshots([without])["quota"] == {}


def test_merge_snapshots_quota_sums_spend_keeps_budgets():
    a = {"quota": {"t1": {"charges": 2, "bytes": 10, "compute_s": 1.0,
                          "budget_bytes": None,
                          "budget_compute_s": None}}}
    b = {"quota": {"t1": {"charges": 1, "bytes": 5, "compute_s": 0.5,
                          "budget_bytes": 1 << 20,
                          "budget_compute_s": 60.0},
                   "t2": {"charges": 9, "bytes": 0, "compute_s": 0.0,
                          "budget_bytes": None,
                          "budget_compute_s": None}}}
    got = tele.merge_snapshots([a, b])["quota"]
    assert got["t1"]["charges"] == 3
    assert got["t1"]["bytes"] == 15
    assert got["t1"]["compute_s"] == pytest.approx(1.5)
    # budgets are configuration: first non-None wins, never summed
    assert got["t1"]["budget_bytes"] == 1 << 20
    assert got["t1"]["budget_compute_s"] == 60.0
    assert got["t2"]["charges"] == 9
