"""Multi-job transform service (adam_tpu/serve): admission control,
weighted fairness, quarantine isolation, graceful drain + durable
journals, and whole-process crash recovery (docs/ROBUSTNESS.md
"Fault-isolated multi-job scheduling").

The pipeline-backed tests run the REAL streamed transform on the numpy
backend (fast, deterministic) and byte-compare every concurrent/
resumed output against a solo fault-free run — the service's core
contract is that scheduling changes where and when work runs, never
the bytes."""

import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from adam_tpu.serve import (
    DONE,
    INTERRUPTED,
    QUARANTINED,
    Admitted,
    Busy,
    JobScheduler,
    JobSpec,
    WeightedInterleaver,
)
from adam_tpu.serve import scheduler as sched_mod
from adam_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parts_hash(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(d)) if f.startswith("part-")
    }


@pytest.fixture(scope="module")
def serve_input(tmp_path_factory):
    """One synthetic input + its solo fault-free baseline (numpy
    backend, window_reads=512) shared by every pipeline-backed test."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from make_synth_sam import make_sam

    work = tmp_path_factory.mktemp("serve")
    path = str(work / "in.sam")
    make_sam(path, 4096, 100)
    solo = str(work / "solo.adam")
    os.environ["ADAM_TPU_BQSR_BACKEND"] = "numpy"
    try:
        from adam_tpu.pipelines.streamed import transform_streamed

        transform_streamed(path, solo, window_reads=512)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    return {"input": path, "baseline": _parts_hash(solo)}


@pytest.fixture()
def numpy_backend(monkeypatch):
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "numpy")


def _spec(jid, serve_input, tmp_path, **kw):
    return JobSpec(
        job_id=jid, input=serve_input["input"],
        output=str(tmp_path / f"{jid}.adam"), window_reads=512, **kw,
    )


# ---------------------------------------------------------------------------
# Fairness interleaver
# ---------------------------------------------------------------------------
def _arbitrate(inter, n):
    """Drive n grant decisions with EVERY lane pinned as waiting — the
    deterministic saturated-backlog view of the WFQ arbitration (the
    threaded paths free-run whenever contention lapses, by design:
    work conservation means a lone waiter never queues, so ratio
    assertions need a pinned backlog)."""
    order = []
    for _ in range(n):
        with inter._lock:
            for seq, lane in enumerate(inter._lanes.values(), 1):
                if lane.waiting_seq is None:
                    lane.waiting_seq = seq
            lane = inter._next_waiter_locked()
            t = inter._tenants[lane.tenant]
            inter._vtime = t.vt
            t.vt += 1.0 / t.weight
            lane.waiting_seq = None
        order.append(lane.job)
    return order


def test_interleaver_weighted_ratio():
    """Two saturated tenants at weights 3:1 interleave exactly 3:1."""
    inter = WeightedInterleaver()
    inter.register("a", tenant="A", weight=3.0)
    inter.register("b", tenant="B", weight=1.0)
    order = _arbitrate(inter, 40)
    assert order.count("a") == 30 and order.count("b") == 10
    # and the interleave is fine-grained, not a 30-then-10 block
    assert "b" in order[:5] and "a" in order[-5:]


def test_interleaver_tenant_shares_allocation():
    """Two jobs of one tenant split that tenant's share — they never
    double it against a single-job tenant of equal weight."""
    inter = WeightedInterleaver()
    inter.register("t1-a", tenant="T1", weight=1.0)
    inter.register("t1-b", tenant="T1", weight=1.0)
    inter.register("t2-z", tenant="T2", weight=1.0)
    order = _arbitrate(inter, 60)
    # equal tenant weights -> tenant T2 owns half the grants even
    # though it runs one job to T1's two
    assert order.count("t2-z") == 30
    assert order.count("t1-a") + order.count("t1-b") == 30


def test_interleaver_threaded_contention_liveness():
    """Concurrent turn() callers all make progress and every grant is
    recorded (the threaded path of the same arbitration)."""
    inter = WeightedInterleaver()
    inter.register("a", tenant="A", weight=2.0)
    inter.register("b", tenant="B", weight=1.0)

    def hammer(jid, n):
        for _ in range(n):
            inter.turn(jid)

    ts = [
        threading.Thread(target=hammer, args=(j, 50))
        for j in ("a", "b")
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
        assert not t.is_alive()
    grants = inter.grant_history()
    assert grants.count("a") == 50 and grants.count("b") == 50


def test_interleaver_solo_and_cancel():
    from adam_tpu.pipelines.streamed import RunCancelled

    inter = WeightedInterleaver()
    inter.register("solo")
    for _ in range(5):
        inter.turn("solo")  # work-conserving: grants immediately
    assert inter.grant_history() == ["solo"] * 5
    inter.cancel()
    with pytest.raises(RunCancelled):
        inter.turn("solo")
    inter.turn("never-registered")  # unregistered jobs free-run


# ---------------------------------------------------------------------------
# Admission control (scheduler with a stubbed pipeline)
# ---------------------------------------------------------------------------
@pytest.fixture()
def stub_transform(monkeypatch):
    """Replace the streamed pipeline with a gate-controlled stub so
    admission tests are timing-free."""
    release = threading.Event()
    started = []

    def fake(inp, out, **kw):
        started.append(out)
        assert release.wait(30), "stub never released"
        return {"n_reads": 0, "windows_fresh": 0}

    monkeypatch.setattr(sched_mod.streamed_mod, "transform_streamed",
                        fake)
    return {"release": release, "started": started}


def test_admission_capacity_and_typed_busy(tmp_path, stub_transform):
    sched = JobScheduler(str(tmp_path / "root"), max_jobs=2)
    try:
        mk = lambda jid: JobSpec(job_id=jid, input="in", output="out")
        assert isinstance(sched.submit(mk("j1")), Admitted)
        assert isinstance(sched.submit(mk("j2")), Admitted)
        got = sched.submit(mk("j3"))
        assert isinstance(got, Busy) and got.kind == "capacity"
        dup = sched.submit(mk("j1"))
        assert isinstance(dup, Busy) and dup.kind == "duplicate"
        # a freed slot admits again
        stub_transform["release"].set()
        assert sched.wait(timeout=30)
        assert isinstance(sched.submit(mk("j3")), Admitted)
        assert sched.wait(timeout=30)
        st = sched.status()["jobs"]
        assert {st[j]["state"] for j in ("j1", "j2", "j3")} == {DONE}
    finally:
        stub_transform["release"].set()
        sched.close()


def test_admission_rejects_while_draining(tmp_path, stub_transform):
    sched = JobScheduler(str(tmp_path / "root"), max_jobs=4)
    try:
        assert isinstance(
            sched.submit(JobSpec(job_id="j1", input="in", output="out")),
            Admitted,
        )
        sched.request_drain()
        got = sched.submit(
            JobSpec(job_id="j2", input="in", output="out")
        )
        assert isinstance(got, Busy) and got.kind == "draining"
        stub_transform["release"].set()
        assert sched.wait(timeout=30)
    finally:
        stub_transform["release"].set()
        sched.close()


def test_submit_blocking_deadline_surfaces_busy(
    tmp_path, stub_transform,
):
    """ISSUE-11 satellite: a full scheduler no longer spins
    `submit_blocking` forever — `deadline_s` bounds the wait through
    retry.call_with_deadline and a typed Busy(capacity) surfaces."""
    from adam_tpu.api.transform_service import TransformService

    svc = TransformService(str(tmp_path / "root"), max_jobs=1)
    try:
        mk = lambda jid: JobSpec(job_id=jid, input="in", output="out")
        assert isinstance(svc.submit(mk("hold")), Admitted)
        t0 = time.monotonic()
        got = svc.submit_blocking(mk("waiter"), deadline_s=0.5,
                                  poll_s=0.05)
        took = time.monotonic() - t0
        assert isinstance(got, Busy) and got.kind == "capacity"
        assert 0.4 <= took < 5.0, took
        # non-capacity rejections return immediately, deadline unused
        t0 = time.monotonic()
        dup = svc.submit_blocking(mk("hold"), deadline_s=30.0)
        assert isinstance(dup, Busy) and dup.kind == "duplicate"
        assert time.monotonic() - t0 < 5.0
        # a freed slot admits within the deadline
        stub_transform["release"].set()
        assert svc.wait(timeout=30)
        got = svc.submit_blocking(mk("waiter"), deadline_s=30.0)
        assert isinstance(got, Admitted)
        assert svc.wait(timeout=30)
    finally:
        stub_transform["release"].set()
        svc.close()


def test_spec_validation_and_manifest(tmp_path):
    with pytest.raises(ValueError):
        JobSpec(job_id="../evil", input="a", output="b").validate()
    with pytest.raises(ValueError):
        JobSpec(job_id="ok", input="a", output="b",
                weight=0).validate()
    from adam_tpu.api.transform_service import load_jobs_manifest

    mpath = tmp_path / "jobs.json"
    mpath.write_text(json.dumps({"jobs": [
        {"job_id": "a", "input": "i", "output": "o", "weight": 2.0},
    ]}))
    (spec,) = load_jobs_manifest(str(mpath))
    assert spec.job_id == "a" and spec.weight == 2.0
    mpath.write_text(json.dumps({"jobs": [
        {"job_id": "a", "input": "i", "output": "o", "nope": 1},
    ]}))
    with pytest.raises(ValueError, match="unknown field"):
        load_jobs_manifest(str(mpath))
    mpath.write_text(json.dumps({"jobs": [
        {"job_id": "a", "input": "i", "output": "o"},
        {"job_id": "a", "input": "i", "output": "p"},
    ]}))
    with pytest.raises(ValueError, match="duplicate"):
        load_jobs_manifest(str(mpath))


def test_pool_lease_bookkeeping():
    from adam_tpu.parallel.device_pool import DevicePool

    pool = DevicePool(limit=2)
    lease = pool.lease(job="jobX")
    assert lease.n == pool.n and lease.devices == pool.devices
    assert [lz.job for lz in pool.active_leases()] == ["jobX"]
    assert lease.device(0) is pool.device(0)
    lease.release()
    lease.release()  # idempotent
    assert pool.active_leases() == []
    assert lease.released


# ---------------------------------------------------------------------------
# Shared 2-device pool: concurrent jobs byte-identical to solo runs
# ---------------------------------------------------------------------------
def test_shared_pool_two_jobs_with_transient_faults(
    tmp_path, serve_input, monkeypatch,
):
    """The ISSUE-10 acceptance scenario: two concurrent jobs share one
    2-virtual-device pool under a transient device.dispatch fault spec
    and each output is byte-identical to its solo single-job run (the
    numpy solo baseline is valid by backend parity, PARITY.md)."""
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "device")
    monkeypatch.setenv("ADAM_TPU_RETRY_BACKOFF_S", "0.001")
    faults.install("device.dispatch=transient,every=7")
    try:
        sched = JobScheduler(str(tmp_path / "root"), max_jobs=2,
                             devices=2)
        a = _spec("pa", serve_input, tmp_path, tenant="A", weight=2.0)
        b = _spec("pb", serve_input, tmp_path, tenant="B", weight=1.0)
        assert isinstance(sched.submit(a), Admitted)
        assert isinstance(sched.submit(b), Admitted)
        assert sched.wait(timeout=600)
        st = sched.status()["jobs"]
        assert all(v["state"] == DONE for v in st.values()), st
        pool = sched._pool
        assert pool is not None and pool.n == 2
        assert pool.active_leases() == []
        for jid in ("pa", "pb"):
            assert _parts_hash(
                str(tmp_path / f"{jid}.adam")
            ) == serve_input["baseline"], jid
        sched.close()
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# Quarantine: one poison job, byte-identical survivors
# ---------------------------------------------------------------------------
def test_quarantine_leaves_survivors_byte_identical(
    tmp_path, serve_input, numpy_backend,
):
    faults.install("sched.job_crash=permanent,device=bad")
    try:
        sched = JobScheduler(str(tmp_path / "root"), max_jobs=2,
                             job_retries=1)
        ok = _spec("ok", serve_input, tmp_path, tenant="A")
        bad = _spec("bad", serve_input, tmp_path, tenant="B")
        assert isinstance(sched.submit(ok), Admitted)
        assert isinstance(sched.submit(bad), Admitted)
        assert sched.wait(timeout=300)
        st = sched.status()["jobs"]
        assert st["ok"]["state"] == DONE
        assert st["bad"]["state"] == QUARANTINED
        assert st["bad"]["attempts"] == 2  # 1 + job_retries
        assert "PermanentFault" in st["bad"]["error"]
        # the survivor's output is byte-identical to its solo run
        assert _parts_hash(
            str(tmp_path / "ok.adam")
        ) == serve_input["baseline"]
        # quarantine frees the slot and holds no lease
        assert sched.status()["active_leases"] == []
        faults.clear()
        retry = _spec("again", serve_input, tmp_path)
        assert isinstance(sched.submit(retry), Admitted)
        assert sched.wait(timeout=300)
        assert sched.status()["jobs"]["again"]["state"] == DONE
        # the quarantined record is durable on disk for the operator
        doc = json.load(
            open(tmp_path / "root" / "bad" / "JOB.json")
        )
        assert doc["state"] == QUARANTINED and doc["attempts"] == 2
        sched.close()
    finally:
        faults.clear()


def test_quarantine_is_sticky_across_restart(
    tmp_path, serve_input, numpy_backend,
):
    faults.install("sched.job_crash=permanent,device=poison")
    try:
        root = str(tmp_path / "root")
        sched = JobScheduler(root, max_jobs=2, job_retries=0)
        assert isinstance(
            sched.submit(_spec("poison", serve_input, tmp_path)),
            Admitted,
        )
        assert sched.wait(timeout=120)
        assert sched.status()["jobs"]["poison"]["state"] == QUARANTINED
        sched.close()
    finally:
        faults.clear()
    # restart: the recovery scan must NOT resume a quarantined job
    sched2 = JobScheduler(root, max_jobs=2)
    assert sched2.recover() == []
    assert sched2.status()["jobs"]["poison"]["state"] == QUARANTINED
    sched2.close()


# ---------------------------------------------------------------------------
# Graceful drain + whole-process restart-resume (in-process)
# ---------------------------------------------------------------------------
def test_drain_then_recover_resumes_bit_identically(
    tmp_path, serve_input, numpy_backend,
):
    root = str(tmp_path / "root")
    sched = JobScheduler(root, max_jobs=2)
    a = _spec("da", serve_input, tmp_path, tenant="A", weight=2.0)
    b = _spec("db", serve_input, tmp_path, tenant="B", weight=1.0)
    assert isinstance(sched.submit(a), Admitted)
    assert isinstance(sched.submit(b), Admitted)
    time.sleep(0.2)
    assert sched.drain(timeout=120)
    st = sched.status()["jobs"]
    for jid in ("da", "db"):
        assert st[jid]["state"] in (INTERRUPTED, DONE), st[jid]
        # drain durability: the on-disk record matches what wait()
        # reported — JOB.json is fsync'd before wait() unblocks
        doc = json.load(open(os.path.join(root, jid, "JOB.json")))
        assert doc["state"] == st[jid]["state"]
    sched.close()

    # "restart the process": a fresh scheduler over the same run-root
    sched2 = JobScheduler(root, max_jobs=2)
    resumed = sched2.recover()
    assert set(resumed) == {
        jid for jid in ("da", "db") if st[jid]["state"] == INTERRUPTED
    }
    assert sched2.wait(timeout=300)
    st2 = sched2.status()["jobs"]
    assert all(v["state"] == DONE for v in st2.values()), st2
    for jid in ("da", "db"):
        assert _parts_hash(
            str(tmp_path / f"{jid}.adam")
        ) == serve_input["baseline"], jid
    sched2.close()


# ---------------------------------------------------------------------------
# SIGTERM drain of the real serve CLI (subprocess)
# ---------------------------------------------------------------------------
_DRIVER = """\
import sys
try:
    import jax, jax._src.xla_bridge as xb
    xb._backend_factories.pop('axon', None)
    jax.config.update('jax_platforms', 'cpu')
except Exception:
    pass
from adam_tpu.cli.main import main
sys.exit(main(sys.argv[1:]))
"""


def _serve_cmd(root, jobs_file):
    return [sys.executable, "-c", _DRIVER, "serve", root,
            "--jobs", jobs_file, "--max-jobs", "2"]


def _serve_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ADAM_TPU_BQSR_BACKEND"] = "numpy"
    env.setdefault("ADAM_TPU_NO_COMPILE_CACHE", "1")
    env.pop("ADAM_TPU_FAULTS", None)
    return env


def test_sigterm_drain_exits_zero_then_resumes(tmp_path, serve_input):
    """SIGTERM mid-flight: exit 0 with durable journals; rerunning the
    same command resumes every job to a byte-identical finish."""
    root = str(tmp_path / "root")
    jobs_file = str(tmp_path / "jobs.json")
    outs = {jid: str(tmp_path / f"{jid}.adam") for jid in ("sa", "sb")}
    with open(jobs_file, "w") as fh:
        json.dump({"jobs": [
            {"job_id": jid, "input": serve_input["input"],
             "output": outs[jid], "window_reads": 512}
            for jid in outs
        ]}, fh)
    proc = subprocess.Popen(
        _serve_cmd(root, jobs_file), env=_serve_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    # wait until both jobs are live (their heartbeat files appear),
    # then request the drain
    deadline = time.monotonic() + 60
    hbs = [os.path.join(root, jid, "heartbeat.ndjson") for jid in outs]
    while time.monotonic() < deadline:
        if all(os.path.isfile(p) for p in hbs):
            break
        if proc.poll() is not None:
            break  # tiny input: the run may simply have finished
        time.sleep(0.05)
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 0, out.decode(errors="replace")
    # resume to completion (no-op when the first run finished)
    rc = subprocess.run(
        _serve_cmd(root, jobs_file), env=_serve_env(), cwd=REPO,
        capture_output=True,
    )
    assert rc.returncode == 0, rc.stdout + rc.stderr
    for jid, out_dir in outs.items():
        assert _parts_hash(out_dir) == serve_input["baseline"], jid
        doc = json.load(open(os.path.join(root, jid, "JOB.json")))
        assert doc["state"] == DONE


# ---------------------------------------------------------------------------
# RunJournal.peek (the recovery scan's read-only view)
# ---------------------------------------------------------------------------
def test_run_journal_peek(tmp_path):
    from adam_tpu.pipelines.checkpoint import RunJournal

    assert RunJournal.peek(str(tmp_path)) is None  # no journal
    j = RunJournal(str(tmp_path), "fp", str(tmp_path / "out"))
    j.confirm_plan(3)
    j.record_window(0, "part-r-00000.parquet")
    got = RunJournal.peek(str(tmp_path))
    assert got == {"fingerprint": "fp", "n_windows": 3, "completed": 1}
    # torn journal -> None, not an exception
    with open(os.path.join(str(tmp_path), RunJournal.JOURNAL_NAME),
              "w") as fh:
        fh.write("{torn")
    assert RunJournal.peek(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# Trace context: one job = one trace across drain/restart attempts
# (docs/OBSERVABILITY.md "Trace context")
# ---------------------------------------------------------------------------
def test_one_job_one_trace_across_drain_and_recovery(
    tmp_path, serve_input, numpy_backend,
):
    import re

    from adam_tpu.utils import telemetry as tele

    root = str(tmp_path / "root")
    sched = JobScheduler(root, max_jobs=2)
    spec = _spec("tj", serve_input, tmp_path)
    assert spec.trace_id is None
    assert isinstance(sched.submit(spec), Admitted)
    # admission minted the trace and persisted it durably
    tid = spec.trace_id
    assert tid and re.fullmatch(r"[0-9a-f]{16}", tid)
    doc = json.load(open(os.path.join(root, "tj", "JOB.json")))
    assert doc["spec"]["trace_id"] == tid
    time.sleep(0.2)
    assert sched.drain(timeout=120)
    first_state = sched.status()["jobs"]["tj"]["state"]
    assert first_state in (INTERRUPTED, DONE)
    sched.close()

    # "restart the process": the recovered spec keeps the SAME trace —
    # however many attempts, one job is one trace
    sched2 = JobScheduler(root, max_jobs=2)
    resumed = sched2.recover()
    if first_state == INTERRUPTED:
        assert resumed == ["tj"]
    assert sched2.wait(timeout=300)
    st = sched2.status()["jobs"]["tj"]
    assert st["state"] == DONE
    assert st["spec"]["trace_id"] == tid
    doc2 = json.load(open(os.path.join(root, "tj", "JOB.json")))
    assert doc2["spec"]["trace_id"] == tid
    sched2.close()

    # the trace is queryable and complete: the scheduler's per-attempt
    # umbrella spans are stamped with it (every attempt, same trace)
    ev = tele.TRACE.events_for_trace(tid)
    sched_runs = [e for e in ev if e["name"] == tele.SPAN_SCHED_JOB]
    assert sched_runs and all(
        e["args"]["job"] == "tj" for e in sched_runs
    )
    # export determinism: two exports of the same trace are byte-equal
    # (what "byte-stable across recovery replay" means for the /trace
    # surface — the view is a pure function of the recorded events)
    d1 = json.dumps(tele.TRACE.to_chrome_trace(tid), sort_keys=True)
    d2 = json.dumps(tele.TRACE.to_chrome_trace(tid), sort_keys=True)
    assert d1 == d2
    # and tracing never touched the output bytes
    assert _parts_hash(str(tmp_path / "tj.adam")) \
        == serve_input["baseline"]
