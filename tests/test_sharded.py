"""Composed sharded transform: boundary correctness vs the single batch.

The contract (parallel/sharded.py): genome-bin shard edges are
invisible — duplicate groups whose mates land in different bins,
realignment targets spanning a bin edge, and the global BQSR table all
resolve exactly as in one batch (MarkDuplicates.scala:66-128,
GenomicPartitioners.scala:63-85).
"""

import os
import sys

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.io import context
from adam_tpu.io.sam import SamHeader, write_sam
from adam_tpu.models.dictionaries import (
    RecordGroup,
    RecordGroupDictionary,
    SequenceDictionary,
    SequenceRecord,
)
from adam_tpu.parallel.sharded import transform_sharded

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)

from tests.test_streamed import _assert_equal  # noqa: E402  (same contract)


def test_sharded_matches_monolithic_wgs(tmp_path):
    from make_wgs_sam import make_wgs

    path = str(tmp_path / "in.sam")
    make_wgs(path, 6000, n_contigs=2, contig_len=40_000)
    mono = (
        context.load_alignments(path)
        .mark_duplicates()
        .realign_indels()
        .recalibrate_base_qualities()
    )
    out = str(tmp_path / "out.adam")
    stats = transform_sharded(path, out, n_shards=4, batch_reads=1024)
    assert stats["n_reads"] == 6000
    back = context.load_alignments(out)
    _assert_equal(mono, back)


def test_sharded_cross_bin_duplicates_and_targets(tmp_path):
    """Mates of duplicate pairs land in different genome bins, and an
    indel target sits exactly on a bin edge: the global resolves must
    see both whole."""
    sd = SequenceDictionary((SequenceRecord("chr1", 90_000),))
    rgd = RecordGroupDictionary((RecordGroup("rg1", library="lib1"),))
    recs = []

    def pair(name, s1, s2, phred):
        tl = s2 + 20 - s1
        r1 = dict(
            name=name, flags=0x1 | 0x20 | 0x40 | 0x2, contig_idx=0,
            start=s1, mapq=60, cigar="20M", seq="A" * 20,
            qual=chr(33 + phred) * 20, read_group_idx=0,
            mate_contig_idx=0, mate_start=s2, tlen=tl, attrs="MD:Z:20",
        )
        r2 = dict(
            name=name, flags=0x1 | 0x10 | 0x80 | 0x2, contig_idx=0,
            start=s2, mapq=60, cigar="20M", seq="A" * 20,
            qual=chr(33 + phred) * 20, read_group_idx=0,
            mate_contig_idx=0, mate_start=s1, tlen=-tl, attrs="MD:Z:20",
        )
        return [r1, r2]

    # duplicate pairs: read1 near the start (bin 0), read2 ~60kb away
    # (a later bin) — with 3 bins over 90kb the mates are in different
    # shards, so per-shard resolution alone would mis-group them
    for i in range(6):
        recs += pair(f"dup{i}", 1_000, 61_000, 30 if i == 4 else 20)
    # an indel read right at the 30kb bin edge plus coverage on both
    # sides: one realignment target with reads in two bins
    recs.append(dict(
        name="indel", flags=0, contig_idx=0, start=29_995, mapq=60,
        cigar="10M2I8M", seq="AAAAAAAAAACCAAAAAAAA", qual="I" * 20,
        read_group_idx=0, attrs="MD:Z:18",
    ))
    for i in range(8):
        recs.append(dict(
            name=f"cover{i}", flags=0, contig_idx=0, start=29_990 + i,
            mapq=60, cigar="20M", seq="A" * 20, qual="I" * 20,
            read_group_idx=0, attrs="MD:Z:20",
        ))
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=sd, read_groups=rgd)
    path = str(tmp_path / "in.sam")
    write_sam(path, batch, side, header)

    mono = (
        context.load_alignments(path)
        .mark_duplicates()
        .realign_indels()
        .recalibrate_base_qualities()
    )
    out = str(tmp_path / "out.adam")
    transform_sharded(path, out, n_shards=3, batch_reads=8)
    back = context.load_alignments(out)
    _assert_equal(mono, back)

    b = back.compact()
    bb = b.batch.to_numpy()
    dup = (np.asarray(bb.flags) & schema.FLAG_DUPLICATE) != 0
    marks = {}
    for i in range(bb.n_rows):
        marks.setdefault(b.sidecar.names[i], []).append(bool(dup[i]))
    # 5 of 6 duplicate pairs marked (both mates), the best pair kept
    assert marks["dup4"] == [False, False]
    n_marked = sum(all(v) for k, v in marks.items() if k.startswith("dup"))
    assert n_marked == 5


def test_raw_shard_round_trip_is_writable(tmp_path):
    """Raw-spill reads must hand back fresh writable arrays (downstream
    transforms mutate columns in place, e.g. trim)."""
    from adam_tpu.io import context
    from adam_tpu.parallel import spill

    ref = os.path.join(
        "/root/reference/adam-core/src/test/resources", "small.sam"
    )
    ds = context.load_alignments(ref)
    p = str(tmp_path / "s.arrows")
    w = spill.RawShardWriter(p)
    w.append(ds.batch, ds.sidecar, ds.header)
    w.close()
    b, side, header = spill.read_raw_shard(p)
    for name in ("bases", "quals", "flags", "start", "cigar_lens"):
        arr = getattr(b, name)
        assert arr.flags.writeable, name
        arr[:1] = arr[:1]  # actually write
    np.testing.assert_array_equal(
        np.asarray(b.start),
        np.asarray(ds.batch.start)[np.asarray(ds.batch.valid)],
    )


def test_raw_shard_round_trip_fuzz(tmp_path):
    """Randomized round-trip of the raw spill: mixed read lengths,
    mixed cigar widths across appends, absent MD/attrs, '*'-qual rows —
    every column must survive bit-for-bit."""
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io.sam import SamHeader
    from adam_tpu.models.dictionaries import (
        SequenceDictionary,
        SequenceRecord,
    )
    from adam_tpu.parallel import spill

    rng = np.random.default_rng(11)
    header = SamHeader(
        seq_dict=SequenceDictionary((SequenceRecord("c1", 10_000),))
    )
    p = str(tmp_path / "f.arrows")
    w = spill.RawShardWriter(p)
    all_recs = []
    for part, L in ((37, 80), (23, 120)):  # widths differ across appends
        recs = []
        for i in range(part):
            ln = int(rng.integers(30, L + 1))
            seq = "".join("ACGTN"[c] for c in rng.integers(0, 5, ln))
            recs.append(dict(
                name=f"r{L}_{i}",
                flags=int(rng.choice([0, 16, 1024, 99])),
                contig_idx=0,
                start=int(rng.integers(0, 5000)),
                mapq=int(rng.integers(0, 61)),
                cigar=(
                    f"{ln}M" if i % 3 else (
                        f"5S{ln - 5}M" if L == 80
                        else f"3S4M2I5M2D{ln - 14}M"
                    )
                ),
                seq=seq,
                qual="*" if i % 7 == 0 else "".join(
                    chr(33 + q) for q in rng.integers(2, 41, ln)
                ),
                md=None if i % 5 == 0 else str(ln),
                attrs=None if i % 4 == 0 else f"NM:i:{i}",
            ))
        batch, side = pack_reads(recs)
        w.append(batch, side, header)
        all_recs.extend(recs)
    w.close()
    b, side, h2 = spill.read_raw_shard(p)
    assert b.n_rows == len(all_recs)
    assert h2.seq_dict.names == ["c1"]
    from adam_tpu.formats import schema
    from adam_tpu.ops.mdtag import parse_cigar

    for i, r in enumerate(all_recs):
        assert side.names[i] == r["name"]
        assert side.md[i] == r["md"]
        # absent attrs may round-trip as either None or ""
        assert (side.attrs[i] or None) == (r["attrs"] or None)
        assert int(b.flags[i]) == r["flags"]
        assert int(b.start[i]) == r["start"]
        assert int(b.contig_idx[i]) == 0
        assert int(b.mapq[i]) == r["mapq"]
        ln = len(r["seq"])
        assert int(b.lengths[i]) == ln
        assert schema.decode_bases(b.bases[i], ln) == r["seq"]
        assert bool(b.has_qual[i]) == (r["qual"] != "*")
        # quals content (mixed widths across appends pad with QUAL_PAD)
        if r["qual"] != "*":
            got_q = (b.quals[i, :ln] + schema.SANGER_OFFSET).tobytes()
            assert got_q == r["qual"].encode()
        # cigar columns survive the i32 pad branch
        exp = parse_cigar(r["cigar"])
        nc = int(b.cigar_n[i])
        assert [
            (int(b.cigar_lens[i, k]),
             schema.CIGAR_CHARS[b.cigar_ops[i, k]])
            for k in range(nc)
        ] == exp
        assert int(b.end[i]) == r["start"] + sum(
            n for n, op in exp if op in "MDN=X"
        )
