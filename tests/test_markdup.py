"""Duplicate-marking scenarios ported from the reference's
MarkDuplicatesSuite (adam-core/src/test/.../read/MarkDuplicatesSuite.scala)."""

import itertools

import numpy as np
import pytest

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.io.sam import SamHeader
from adam_tpu.models.dictionaries import (
    RecordGroup,
    RecordGroupDictionary,
    SequenceDictionary,
    SequenceRecord,
)

_counter = itertools.count()

CONTIGS = ["0", "1", "2", "10", "ref0", "ref1"]
SD = SequenceDictionary(tuple(SequenceRecord(n, 10_000_000) for n in CONTIGS))
RGD = RecordGroupDictionary((RecordGroup("machine foo", library="library bar"),))


def mapped_read(ref, start, name=None, phred=20, clipped=0, neg=False,
                primary=True, **kw):
    name = name or f"read{next(_counter)}"
    cigar = f"{clipped}S{100 - clipped}M" if clipped else "100M"
    flags = (0x10 if neg else 0) | (0 if primary else 0x100)
    return dict(
        name=name, flags=flags, contig_idx=SD.index(ref), start=start,
        mapq=60, cigar=cigar, seq="A" * 100, qual=chr(phred + 33) * 100,
        read_group_idx=0, **kw,
    )


def unmapped_read(name=None):
    return dict(
        name=name or f"read{next(_counter)}", flags=0x4, contig_idx=-1,
        start=-1, mapq=0, cigar="*", seq="A" * 100, qual="5" * 100,
        read_group_idx=0,
    )


def pair(ref1, s1, ref2, s2, name=None, phred=20):
    name = name or f"pair{next(_counter)}"
    r1 = mapped_read(ref1, s1, name=name, phred=phred)
    r1["flags"] |= 0x1 | 0x40
    r1["mate_contig_idx"] = SD.index(ref2)
    r1["mate_start"] = s2
    r2 = mapped_read(ref2, s2, name=name, phred=phred, neg=True)
    r2["flags"] |= 0x1 | 0x80
    r2["mate_contig_idx"] = SD.index(ref1)
    r2["mate_start"] = s1
    return [r1, r2]


def run_markdup(recs):
    batch, side = pack_reads(recs)
    ds = AlignmentDataset(batch, side, SamHeader(seq_dict=SD, read_groups=RGD))
    out = ds.mark_duplicates()
    b = out.batch.to_numpy()
    dup = (np.asarray(b.flags) & schema.FLAG_DUPLICATE) != 0
    return {out.sidecar.names[i]: bool(dup[i]) for i in range(b.n_rows) if b.valid[i]}, out


def dup_names(dups):
    return {n for n, d in dups.items() if d}


def test_single_read():
    dups, _ = run_markdup([mapped_read("0", 100)])
    assert not any(dups.values())


def test_reads_at_different_positions():
    dups, _ = run_markdup([mapped_read("0", 42), mapped_read("0", 43)])
    assert not any(dups.values())


def test_reads_at_same_position():
    recs = [mapped_read("1", 42, name="best", phred=30)] + [
        mapped_read("1", 42, name=f"poor{i}", phred=20) for i in range(10)
    ]
    dups, _ = run_markdup(recs)
    assert not dups["best"]
    assert dup_names(dups) == {f"poor{i}" for i in range(10)}


def test_reads_at_same_position_with_clipping():
    recs = (
        [mapped_read("1", 42, name="best", phred=30)]
        + [mapped_read("1", 44, name=f"poorClipped{i}", clipped=2) for i in range(5)]
        + [mapped_read("1", 42, name=f"poorUnclipped{i}") for i in range(5)]
    )
    dups, _ = run_markdup(recs)
    assert not dups["best"]
    assert len(dup_names(dups)) == 10


def test_reads_on_reverse_strand():
    recs = [mapped_read("10", 42, name="best", phred=30, neg=True)] + [
        mapped_read("10", 42, name=f"poor{i}", neg=True) for i in range(7)
    ]
    dups, _ = run_markdup(recs)
    assert not dups["best"]
    assert len(dup_names(dups)) == 7


def test_unmapped_reads():
    dups, _ = run_markdup([unmapped_read(f"u{i}") for i in range(10)])
    assert not any(dups.values())


def test_read_pairs():
    recs = pair("0", 10, "0", 110, name="best", phred=30)
    for i in range(10):
        recs += pair("0", 10, "0", 110, name=f"poor{i}")
    dups, _ = run_markdup(recs)
    assert not dups["best"]
    assert dup_names(dups) == {f"poor{i}" for i in range(10)}


def test_read_pairs_with_fragments():
    """Pairs always beat fragments at the same left position, regardless
    of score."""
    recs = [mapped_read("2", 33, name=f"fragment{i}", phred=40) for i in range(10)]
    recs += pair("2", 33, "2", 100, name="pair", phred=20)
    dups, _ = run_markdup(recs)
    assert not dups["pair"]
    assert dup_names(dups) == {f"fragment{i}" for i in range(10)}


def test_quality_score():
    """Score = sum of phred >= 15 (MarkDuplicates.scala:45-47)."""
    from adam_tpu.pipelines.markdup import row_summary

    batch, side = pack_reads(
        [
            mapped_read("0", 1, phred=20),
            dict(name="mixed", flags=0, contig_idx=0, start=1, mapq=60,
                 cigar="4M", seq="ACGT", qual=chr(33 + 20) * 2 + chr(33 + 10) * 2,
                 read_group_idx=0),
        ]
    )
    ds = AlignmentDataset(batch, side, SamHeader())
    score = row_summary(ds)["score"]
    assert int(np.asarray(score)[0]) == 2000
    assert int(np.asarray(score)[1]) == 40  # phred-10 bases don't count


def test_read_pairs_cross_chromosome():
    recs = pair("ref0", 10, "ref1", 110, name="best", phred=30)
    for i in range(10):
        recs += pair("ref0", 10, "ref1", 110, name=f"poor{i}")
    dups, _ = run_markdup(recs)
    assert not dups["best"]
    assert dup_names(dups) == {f"poor{i}" for i in range(10)}


def test_secondary_alignments_marked_with_bucket():
    """Secondary alignments of the best bucket are still duplicates."""
    best = [mapped_read("1", 42, name="best", phred=30),
            mapped_read("1", 42, name="best", phred=30, primary=False)]
    poor = [mapped_read("1", 42, name="poor", phred=20)]
    dups, out = run_markdup(best + poor)
    b = out.batch.to_numpy()
    flags = np.asarray(b.flags)
    by_name = {}
    for i in range(b.n_rows):
        key = (out.sidecar.names[i], bool(flags[i] & 0x100))
        by_name[key] = bool(flags[i] & schema.FLAG_DUPLICATE)
    assert by_name[("best", False)] is False
    assert by_name[("best", True)] is True  # secondary of winner still dup
    assert by_name[("poor", False)] is True
