"""Static contract checker tests (adam_tpu/staticcheck).

Three layers: engine mechanics (suppressions, baseline round-trip,
exit codes, JSON schema), per-rule fixture snippets (each rule must
catch its seeded violation and stay quiet on the compliant twin), and
the clean-repo gate (the real tree reports zero new findings and every
baseline entry is justified — the acceptance bar of ISSUE 9)."""

import json
import os
import textwrap

import pytest

from adam_tpu.staticcheck import core


def _write(root, relpath, src):
    path = os.path.join(str(root), relpath)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # @NOQA@ keeps suppression directives out of THIS file's lines (the
    # checker line-scans the real test tree for directives)
    src = textwrap.dedent(src).replace("@NOQA@", "adam-tpu: noqa")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(src)
    return path


#: durability's scope is an explicit file list — fixtures must land on it
DURABLE_FILE = "adam_tpu/pipelines/checkpoint.py"


def _run(root, rules, baseline=None, update=False):
    return core.run_checks(
        str(root), rule_names=rules,
        baseline_path=baseline or os.path.join(str(root), "bl.json"),
        update_baseline=update,
    )


def _new(report, rule=None):
    return [e for e in report.new_findings
            if rule is None or e["rule"] == rule]


# -------------------------------------------------------------------------
# engine
# -------------------------------------------------------------------------
def test_suppression_requires_reason(tmp_path):
    _write(tmp_path, DURABLE_FILE, """\
        import os
        def f(path):
            os.replace(path, path + ".pub")  # @NOQA@[durability]
    """)
    rep = _run(tmp_path, ["durability"])
    # the durability finding is NOT silenced (no reason) and the
    # directive itself is reported
    rules = sorted(e["rule"] for e in rep.new_findings)
    assert rules == ["durability", "suppression"]
    assert not rep.ok


def test_suppression_with_reason_silences(tmp_path):
    _write(tmp_path, DURABLE_FILE, """\
        import os
        def f(path):
            os.replace(path, path + ".pub")  # @NOQA@[durability] reason=unit fixture
    """)
    rep = _run(tmp_path, ["durability"])
    assert rep.ok
    assert rep.counts()["suppressed"] == 1


def test_suppression_on_preceding_comment_line(tmp_path):
    _write(tmp_path, DURABLE_FILE, """\
        import os
        def f(path):
            # @NOQA@[durability] reason=publish is fsynced by the caller
            os.replace(path, path + ".pub")
    """)
    rep = _run(tmp_path, ["durability"])
    assert rep.ok and rep.counts()["suppressed"] == 1


def test_baseline_round_trip(tmp_path):
    rel = DURABLE_FILE
    _write(tmp_path, rel, """\
        import os
        def f(path):
            os.replace(path, path + ".pub")
    """)
    bl = os.path.join(str(tmp_path), "bl.json")
    # 1. finding is new
    rep = _run(tmp_path, ["durability"], baseline=bl)
    assert len(_new(rep, "durability")) == 1
    # 2. update writes the baseline; entry still fails (no reason yet)
    rep = _run(tmp_path, ["durability"], baseline=bl, update=True)
    entries = core.load_baseline(bl)
    assert len(entries) == 1
    rep = _run(tmp_path, ["durability"], baseline=bl)
    assert not rep.ok  # unjustified baseline entry
    # 3. justify -> clean, reported as baselined
    (fp, e), = entries.items()
    e["reason"] = "triaged in the unit fixture"
    core.write_baseline(bl, [e])
    rep = _run(tmp_path, ["durability"], baseline=bl)
    assert rep.ok and rep.counts()["baselined"] == 1
    # 4. fix the code -> the entry is stale and fails the run
    _write(tmp_path, rel, "def f(path):\n    return path\n")
    rep = _run(tmp_path, ["durability"], baseline=bl)
    assert not rep.ok
    assert any(x["rule"] == "baseline" for x in rep.new_findings)


def test_baseline_subset_run_keeps_other_rules(tmp_path):
    """A --rules subset run must neither condemn nor drop baseline
    entries belonging to the rules that did not run."""
    bl = os.path.join(str(tmp_path), "bl.json")
    core.write_baseline(bl, [{
        "fingerprint": "0" * 16, "rule": "host-sync",
        "path": "adam_tpu/pipelines/y.py", "line": 1, "snippet": "x",
        "reason": "belongs to a rule not in this run",
    }])
    _write(tmp_path, "adam_tpu/pipelines/x.py", "VALUE = 1\n")
    rep = _run(tmp_path, ["durability"], baseline=bl)
    assert rep.ok, rep.new_findings
    rep = _run(tmp_path, ["durability"], baseline=bl, update=True)
    assert "0" * 16 in core.load_baseline(bl)


def test_suppressing_a_baselined_finding_is_not_stale(tmp_path):
    """Adding a noqa to a line whose finding is baselined must not
    report the baseline entry as stale — the finding still exists."""
    rel = DURABLE_FILE
    _write(tmp_path, rel, """\
        import os
        def f(path):
            os.replace(path, path + ".pub")
    """)
    bl = os.path.join(str(tmp_path), "bl.json")
    _run(tmp_path, ["durability"], baseline=bl, update=True)
    entries = list(core.load_baseline(bl).values())
    entries[0]["reason"] = "triaged"
    core.write_baseline(bl, entries)
    # preceding-line directive: the flagged line's text (the
    # fingerprint anchor) is unchanged, so the entry must match — as
    # suppressed — rather than read as stale
    _write(tmp_path, rel, """\
        import os
        def f(path):
            # @NOQA@[durability] reason=now suppressed in place
            os.replace(path, path + ".pub")
    """)
    rep = _run(tmp_path, ["durability"], baseline=bl)
    assert not any(e["rule"] == "baseline" for e in rep.new_findings), \
        rep.new_findings
    assert rep.ok


def test_unused_suppression_reported(tmp_path):
    _write(tmp_path, DURABLE_FILE, """\
        import os
        def f(path):
            return path  # @NOQA@[durability] reason=nothing fires here anymore
    """)
    rep = _run(tmp_path, ["durability"])
    assert not rep.ok
    assert any(e["rule"] == "suppression"
               and "unused suppression" in e["message"]
               for e in rep.new_findings)
    # but a subset run for a DIFFERENT rule must not condemn it
    rep = _run(tmp_path, ["fault-registry"])
    assert not any("unused suppression" in e["message"]
                   for e in rep.new_findings)
    # and --update-baseline must not absorb suppression-hygiene
    # findings into the baseline (they are fixed in place)
    bl = os.path.join(str(tmp_path), "bl.json")
    _run(tmp_path, ["durability"], baseline=bl, update=True)
    assert core.load_baseline(bl) == {}


def test_json_schema_and_exit_codes(tmp_path):
    _write(tmp_path, "adam_tpu/pipelines/x.py", "VALUE = 1\n")
    rep = _run(tmp_path, ["durability"])
    doc = rep.to_json()
    assert doc["schema"] == "adam_tpu.staticcheck/1"
    for key in ("root", "rules", "counts", "findings", "ok"):
        assert key in doc
    assert rep.exit_code == core.EXIT_CLEAN
    _write(tmp_path, DURABLE_FILE, """\
        import os
        def f(p):
            os.replace(p, p)
    """)
    assert _run(tmp_path, ["durability"]).exit_code == core.EXIT_FINDINGS
    with pytest.raises(ValueError):
        core.run_checks(str(tmp_path), rule_names=["no-such-rule"])


def test_plugin_rule_registration(tmp_path, monkeypatch):
    mod = _write(tmp_path, "myplugin.py", """\
        from adam_tpu.staticcheck.core import Rule

        class EveryFile(Rule):
            name = "every-file"
            summary = "fires once per file"
            def visit(self, ctx):
                yield ctx.finding(self.name, ctx.tree, "seen")

        RULES = [EveryFile]
    """)
    monkeypatch.syspath_prepend(os.path.dirname(mod))
    # plugin registration is process-global by design; keep this test
    # from leaking its rule into the other tests' full-registry runs
    core._load_builtins()
    monkeypatch.setattr(core, "_REGISTRY", dict(core._REGISTRY))
    _write(tmp_path, "adam_tpu/pipelines/x.py", "VALUE = 1\n")
    rep = core.run_checks(
        str(tmp_path), rule_names=["every-file"], plugins=["myplugin"],
        baseline_path=os.path.join(str(tmp_path), "bl.json"),
    )
    assert len(_new(rep, "every-file")) >= 1


# -------------------------------------------------------------------------
# host-sync
# -------------------------------------------------------------------------
HOT = "adam_tpu/pipelines/hot.py"


def test_hostsync_flags_asarray_on_jit_result(tmp_path):
    _write(tmp_path, HOT, """\
        import jax
        import numpy as np

        @jax.jit
        def my_kernel(x):
            return x + 1

        def run(x):
            out = my_kernel(x)
            return np.asarray(out)
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert len(_new(rep, "host-sync")) == 1


def test_hostsync_device_fetch_launders(tmp_path):
    _write(tmp_path, HOT, """\
        import jax
        import numpy as np
        from adam_tpu.utils.transfer import device_fetch

        @jax.jit
        def my_kernel(x):
            return x + 1

        def run(x):
            out = device_fetch(my_kernel(x))
            return np.asarray(out), int(out.sum())
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert _new(rep, "host-sync") == []


def test_hostsync_taint_flows_through_unpack_and_methods(tmp_path):
    _write(tmp_path, HOT, """\
        import jax
        import numpy as np

        @jax.jit
        def pair_kernel(x):
            return x, x

        def run(x):
            a, b = pair_kernel(x)
            c = a.astype("int32")[:4]
            return float(c.sum()), b.item()
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert len(_new(rep, "host-sync")) == 2  # float(...) and .item()


def test_hostsync_isinstance_guard_narrows(tmp_path):
    _write(tmp_path, HOT, """\
        import jax
        import numpy as np

        @jax.jit
        def my_kernel(x):
            return x + 1

        def run(x):
            out = my_kernel(x)
            if isinstance(out, np.ndarray):
                return np.asarray(out)
            return None
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert _new(rep, "host-sync") == []


def test_hostsync_warm_and_out_of_scope_exempt(tmp_path):
    src = """\
        import jax

        def warm_shapes(k):
            jax.block_until_ready(k)

        def probe_device(k):
            return float(k)
    """
    _write(tmp_path, HOT, src)
    _write(tmp_path, "adam_tpu/utils/helper.py",
           "import jax\n\ndef f(x):\n    jax.block_until_ready(x)\n")
    rep = _run(tmp_path, ["host-sync"])
    assert _new(rep, "host-sync") == []  # warm fns + utils/ out of scope


def test_hostsync_else_branch_taint_survives_join(tmp_path):
    _write(tmp_path, HOT, """\
        import jax
        import numpy as np

        @jax.jit
        def my_kernel(x):
            return x + 1

        def run(x, cond):
            if cond:
                out = np.zeros(3)
            else:
                out = my_kernel(x)
            return np.asarray(out)
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert len(_new(rep, "host-sync")) == 1


def test_hostsync_comprehension_taint(tmp_path):
    _write(tmp_path, HOT, """\
        import jax
        import numpy as np

        @jax.jit
        def my_kernel(x):
            return x + 1

        def run(xs):
            vals = [my_kernel(x) for x in xs]
            return np.asarray(vals[0])
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert len(_new(rep, "host-sync")) == 1


def test_hostsync_conditional_def_walked_once(tmp_path):
    # a def nested in a module-level try/if must yield ONE finding,
    # not a duplicate pair with two fingerprints
    _write(tmp_path, HOT, """\
        import jax
        import numpy as np

        @jax.jit
        def my_kernel(x):
            return x + 1

        try:
            import fastpath
        except ImportError:
            def fallback(a):
                return np.asarray(my_kernel(a))
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert len(_new(rep, "host-sync")) == 1


def test_hostsync_flags_block_until_ready_and_device_get(tmp_path):
    _write(tmp_path, HOT, """\
        import jax

        def run(x):
            jax.block_until_ready(x)
            return jax.device_get(x)
    """)
    rep = _run(tmp_path, ["host-sync"])
    assert len(_new(rep, "host-sync")) == 2


# -------------------------------------------------------------------------
# dispatch-ledger
# -------------------------------------------------------------------------
DISPATCH_FILE = "adam_tpu/pipelines/streamed.py"  # in the rule's scope


def test_dispatch_untracked_flagged_tracked_ok(tmp_path):
    _write(tmp_path, DISPATCH_FILE, """\
        from adam_tpu.utils import compile_ledger

        def bad(b, observe_kernel):
            return observe_kernel(b)

        def good(b, observe_kernel, dev):
            with compile_ledger.track(("k.observe", 8), dev):
                return observe_kernel(b)
    """)
    rep = _run(tmp_path, ["dispatch-ledger"])
    flagged = _new(rep, "dispatch-ledger")
    dispatch = [f for f in flagged if "outside compile_ledger" in f["message"]]
    assert len(dispatch) == 1 and dispatch[0]["line"] == 4


def test_dispatch_nested_def_retry_idiom_covered(tmp_path):
    _write(tmp_path, DISPATCH_FILE, """\
        from adam_tpu.utils import compile_ledger
        from adam_tpu.utils import retry as _retry

        def run(b, observe_kernel, dev):
            def dispatch():
                return observe_kernel(b)

            with compile_ledger.track(("k.observe", 8), dev):
                return _retry.retry_call(dispatch, site="x")
    """)
    rep = _run(tmp_path, ["dispatch-ledger"])
    assert not [f for f in _new(rep, "dispatch-ledger")
                if "outside compile_ledger" in f["message"]]


def test_dispatch_prewarm_cross_check(tmp_path):
    # a tracked kernel whose key no prewarm entry builds is flagged;
    # one with an entry is not
    _write(tmp_path, DISPATCH_FILE, """\
        from adam_tpu.utils import compile_ledger

        def run(b, my_kernel, dev):
            with compile_ledger.track(("k.orphan", 8), dev):
                my_kernel(b)
            with compile_ledger.track(("k.covered", 8), dev):
                my_kernel(b)
    """)
    _write(tmp_path, "adam_tpu/parallel/device_pool.py", """\
        def covered_prewarm_entry(g):
            def warm(dev):
                pass
            return (("k.covered", g), warm)
    """)
    rep = _run(tmp_path, ["dispatch-ledger"])
    orphans = [f for f in _new(rep, "dispatch-ledger")
               if "no prewarm registry entry" in f["message"]]
    assert len(orphans) == 1 and "k.orphan" in orphans[0]["message"]


def test_dispatch_trace_time_calls_exempt(tmp_path):
    _write(tmp_path, DISPATCH_FILE, """\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def outer_kernel(x, n):
            return inner_kernel(x) + n

        def inner_kernel(x):
            return x
    """)
    rep = _run(tmp_path, ["dispatch-ledger"])
    assert not [f for f in _new(rep, "dispatch-ledger")
                if "outside compile_ledger" in f["message"]]


# -------------------------------------------------------------------------
# durability
# -------------------------------------------------------------------------
def test_durability_primitives_flagged(tmp_path):
    _write(tmp_path, "adam_tpu/pipelines/checkpoint.py", """\
        import json
        import os

        def publish(doc, path):
            with open(path, "w") as fh:
                json.dump(doc, fh)
            os.replace(path, path + ".final")
    """)
    rep = _run(tmp_path, ["durability"])
    msgs = "\\n".join(f["message"] for f in _new(rep, "durability"))
    assert "os.replace" in msgs and "json.dump" in msgs and "open" in msgs
    assert len(_new(rep, "durability")) == 3


def test_durability_staging_and_reads_ok(tmp_path):
    _write(tmp_path, "adam_tpu/pipelines/checkpoint.py", """\
        from adam_tpu.utils.durability import atomic_write_json, publish_file

        def publish(doc, path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(b"x")
            publish_file(tmp, path)
            atomic_write_json(path + ".json", doc)
            with open(path, "rb") as fh:
                return fh.read()
    """)
    rep = _run(tmp_path, ["durability"])
    assert _new(rep, "durability") == []


# -------------------------------------------------------------------------
# fault-registry
# -------------------------------------------------------------------------
FIXTURE_FAULTS = """\
    KNOWN_POINTS = frozenset({
        "device.fetch",
        "ghost.point",
    })

    def point(site, device=None):
        pass
"""


def test_fault_registry_unknown_site_and_unused_member(tmp_path):
    _write(tmp_path, "adam_tpu/utils/faults.py", FIXTURE_FAULTS)
    _write(tmp_path, "adam_tpu/pipelines/x.py", """\
        from adam_tpu.utils import faults

        def f():
            faults.point("device.fetch")
            faults.point("device.typo")
    """)
    rep = _run(tmp_path, ["fault-registry"])
    msgs = [f["message"] for f in _new(rep, "fault-registry")]
    assert any("device.typo" in m and "not in faults.KNOWN_POINTS" in m
               for m in msgs)
    assert any("ghost.point" in m and "no faults.point call site" in m
               for m in msgs)


def test_fault_registry_docs_gap(tmp_path):
    _write(tmp_path, "adam_tpu/utils/faults.py", """\
        KNOWN_POINTS = frozenset({"device.fetch"})
    """)
    _write(tmp_path, "adam_tpu/pipelines/x.py", """\
        from adam_tpu.utils import faults

        def f():
            faults.point("device.fetch")
    """)
    # no docs file: the docs check degrades to skipped
    rep = _run(tmp_path, ["fault-registry"])
    assert rep.ok
    _write(tmp_path, "docs/ROBUSTNESS.md", "fault points: (none listed)\n")
    rep = _run(tmp_path, ["fault-registry"])
    assert any("missing from docs/ROBUSTNESS.md" in f["message"]
               for f in _new(rep, "fault-registry"))


# -------------------------------------------------------------------------
# lock-discipline
# -------------------------------------------------------------------------
def test_lock_module_global_mutation(tmp_path):
    _write(tmp_path, "adam_tpu/utils/pool.py", """\
        import threading

        _SEEN = set()
        _LOCK = threading.Lock()
        ENABLED = False

        def spawn():
            threading.Thread(target=lambda: None).start()

        def bad(key):
            global ENABLED
            _SEEN.add(key)
            ENABLED = True

        def good(key):
            global ENABLED
            with _LOCK:
                _SEEN.add(key)
                ENABLED = True
    """)
    rep = _run(tmp_path, ["lock-discipline"])
    flagged = _new(rep, "lock-discipline")
    assert len(flagged) == 2
    assert all(f["line"] in (12, 13) for f in flagged)


def test_lock_class_discipline_and_locked_convention(tmp_path):
    _write(tmp_path, "adam_tpu/utils/reg.py", """\
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = {}

            def _get_locked(self, k):
                if k not in self.items:
                    self.items[k] = 0
                return self.items[k]

            def bad_call(self, k):
                return self._get_locked(k)

            def bad_mutate(self, k):
                self.items[k] = 1

            def good(self, k):
                with self._lock:
                    self.items[k] = self._get_locked(k) + 1
    """)
    rep = _run(tmp_path, ["lock-discipline"])
    msgs = [f["message"] for f in _new(rep, "lock-discipline")]
    assert len(msgs) == 2
    assert any("_get_locked" in m for m in msgs)
    assert any("item assignment" in m for m in msgs)


def test_lock_quiet_without_threads_or_lock(tmp_path):
    _write(tmp_path, "adam_tpu/utils/simple.py", """\
        CACHE = {}

        def put(k, v):
            CACHE[k] = v
    """)
    rep = _run(tmp_path, ["lock-discipline"])
    assert rep.ok  # no thread spawn, no lock-owning class: out of scope


# -------------------------------------------------------------------------
# telemetry-contract
# -------------------------------------------------------------------------
FIXTURE_TELE = """\
    _R = set()

    def _span(name):
        _R.add(name)
        return name

    def _metric(name):
        _R.add(name)
        return name

    SPAN_GOOD = _span("pipeline.good")
    HEARTBEAT_FIELDS = ("schema", "undocumented_field")
"""


def test_telemetry_undeclared_name(tmp_path):
    _write(tmp_path, "adam_tpu/utils/telemetry.py", FIXTURE_TELE)
    _write(tmp_path, "adam_tpu/pipelines/x.py", """\
        def f(tr):
            with tr.span("pipeline.good"):
                tr.count("pipeline.rogue")
    """)
    rep = _run(tmp_path, ["telemetry-contract"])
    flagged = _new(rep, "telemetry-contract")
    assert len(flagged) == 1 and "pipeline.rogue" in flagged[0]["message"]


def test_telemetry_docs_gaps(tmp_path):
    _write(tmp_path, "adam_tpu/utils/telemetry.py", FIXTURE_TELE)
    _write(tmp_path, "docs/OBSERVABILITY.md",
           "names: `schema` only is documented here\n")
    rep = _run(tmp_path, ["telemetry-contract"])
    msgs = [f["message"] for f in _new(rep, "telemetry-contract")]
    assert any("pipeline.good" in m and "name contract" in m for m in msgs)
    assert any("undocumented_field" in m for m in msgs)


def test_telemetry_prometheus_collision(tmp_path):
    """Two dotted names that merge under '.' -> '_' mangling — the
    silent-series-merge the Prometheus leg of the rule exists to
    catch."""
    _write(tmp_path, "adam_tpu/utils/telemetry.py", """\
        _R = set()

        def _metric(name):
            _R.add(name)
            return name

        C_A = _metric("sched.batch.fill")
        C_B = _metric("sched.batch_fill")
        HEARTBEAT_FIELDS = ("schema",)
    """)
    _write(tmp_path, "docs/OBSERVABILITY.md",
           "`sched.batch.fill` `sched.batch_fill` `schema`\n")
    rep = _run(tmp_path, ["telemetry-contract"])
    msgs = [f["message"] for f in _new(rep, "telemetry-contract")]
    assert any(
        "collide" in m and "adam_tpu_sched_batch_fill" in m for m in msgs
    ), msgs


def test_telemetry_prometheus_display_names_exempt(tmp_path):
    """Display-style instrumentation timer names (spaces, parens) sit
    outside the dotted contract: the renderer sanitizes them, the
    mangling lint must not flag them."""
    _write(tmp_path, "adam_tpu/utils/telemetry.py", """\
        _R = set()

        def _metric(name):
            _R.add(name)
            return name

        C_A = _metric("reads.ingested")
        C_B = _metric("BGZF Codec (native)")
        HEARTBEAT_FIELDS = ("schema",)
    """)
    _write(tmp_path, "docs/OBSERVABILITY.md",
           "`reads.ingested` `schema`\n")
    rep = _run(tmp_path, ["telemetry-contract"])
    assert _new(rep, "telemetry-contract") == []


def test_telemetry_rule_literals_pin_registry():
    """The rule keeps its own PROMETHEUS_PREFIX / validity-regex
    literals (so it lints foreign trees without importing them) — pin
    them against the registry's, and pin the regex against
    telemetry.prometheus_name_valid on both sides of the grammar."""
    from adam_tpu.staticcheck.rules import telemetry_names as rule_mod
    from adam_tpu.utils import telemetry as tele

    assert rule_mod.PROMETHEUS_PREFIX == tele.PROMETHEUS_PREFIX
    for probe, ok in (
        ("adam_tpu_reads_ingested", True),
        ("adam_tpu_x:y", True),
        ("9leading_digit", False),
        ("adam_tpu_bad name", False),
        ("adam_tpu_bad-name", False),
    ):
        assert bool(rule_mod._PROM_NAME_RE.fullmatch(probe)) == ok == \
            tele.prometheus_name_valid(probe), probe


# -------------------------------------------------------------------------
# the clean-repo gate + CLI
# -------------------------------------------------------------------------
def _repo_root():
    import adam_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        adam_tpu.__file__
    )))


def test_repo_is_clean():
    """The acceptance bar: `adam-tpu check` runs clean on this repo —
    zero new findings, and every baselined finding carries a
    justification (ISSUE 9 acceptance criteria)."""
    rep = core.run_checks(_repo_root())
    assert rep.parse_errors == []
    assert rep.new_findings == [], "\n".join(
        f"{e['path']}:{e['line']}: [{e['rule']}] {e['message']}"
        for e in rep.new_findings
    )
    for e in rep.entries:
        if e["status"] == "baselined":
            assert e["reason"], f"unjustified baseline entry: {e}"


def test_repo_seeded_violation_is_caught(tmp_path):
    """End-to-end sanity that the gate is live: the same engine that
    passes the clean repo fails a tree seeded with one violation."""
    root = _repo_root()
    rel = "adam_tpu/pipelines/checkpoint.py"
    _write(tmp_path, rel, """\
        import os

        def f(p):
            os.replace(p, p + ".pub")
    """)
    # scan the seeded file against the REAL repo root configuration by
    # handing the engine an explicit file list rooted at the fixture
    rep = core.run_checks(
        str(tmp_path), rule_names=["durability"],
        files=[os.path.join(str(tmp_path), rel)],
        baseline_path=os.path.join(str(tmp_path), "bl.json"),
    )
    assert not rep.ok
    del root


def test_cli_check_json(tmp_path, capsys):
    from adam_tpu.cli.main import main

    out_path = str(tmp_path / "report.json")
    rc = main(["check", "--json", out_path, "--quiet"])
    assert rc == 0
    with open(out_path) as fh:
        doc = json.load(fh)
    assert doc["schema"] == "adam_tpu.staticcheck/1"
    assert doc["ok"] is True and doc["counts"]["new"] == 0


def test_cli_check_list_rules(capsys):
    from adam_tpu.cli.main import main

    assert main(["check", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("host-sync", "dispatch-ledger", "durability",
                 "fault-registry", "lock-discipline",
                 "telemetry-contract"):
        assert rule in out


def test_check_telemetry_names_wrapper():
    """The absorbed script keeps its contract: exit 0 + summary line."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.join(_repo_root(), "scripts",
                                      "check-telemetry-names")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "telemetry name contract OK" in proc.stdout
