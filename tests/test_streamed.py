"""Streamed overlapped transform: equality with the monolithic path.

The pipeline contract (pipelines/streamed.py) is that window edges are
invisible: duplicate groups, BQSR statistics and realignment targets
that span two ingest windows must resolve exactly as in one batch — the
same boundary-correctness the sharded path needs
(rdd/read/MarkDuplicates.scala:66-128, GenomicPartitioners.scala:63-85).
"""

import os
import sys

import numpy as np
import pytest

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.strings import StringColumn
from adam_tpu.io import context
from adam_tpu.pipelines.streamed import transform_streamed

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


def _row_table(ds):
    """Window-order-independent view: rows keyed by (name, flags)."""
    d = ds.compact()
    b = d.batch.to_numpy()
    names = StringColumn.of(d.sidecar.names).to_fixed_bytes().astype("S64")
    order = np.lexsort((np.asarray(b.flags), names))
    cols = {
        f: np.asarray(getattr(b, f))[order]
        for f in ["flags", "start", "end", "mapq", "lengths", "contig_idx",
                  "cigar_n"]
    }
    cols["names"] = names[order]
    L = b.lmax
    cols["quals"] = np.asarray(b.quals)[order][:, :L]
    side = d.sidecar
    cols["md"] = [side.md[i] for i in order]
    cols["attrs"] = [side.attrs[i] for i in order]
    cols["oq"] = [side.orig_quals[i] for i in order]
    return cols


def _assert_equal(mono, streamed):
    a, b = _row_table(mono), _row_table(streamed)
    np.testing.assert_array_equal(a["names"], b["names"])
    for f in ["flags", "start", "end", "mapq", "lengths", "contig_idx",
              "cigar_n"]:
        np.testing.assert_array_equal(a[f], b[f], err_msg=f)
    L = min(a["quals"].shape[1], b["quals"].shape[1])
    np.testing.assert_array_equal(a["quals"][:, :L], b["quals"][:, :L])
    assert a["md"] == b["md"]
    assert a["attrs"] == b["attrs"]
    assert a["oq"] == b["oq"]


def test_streamed_matches_monolithic(tmp_path):
    """8 windows of a synthetic WGS-shaped file: identical output rows
    (flags incl. duplicate marks, recalibrated quals, realigned cigars,
    MD/OQ/attrs) vs load-then-stage-by-stage."""
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 8192, 100)
    mono = (
        context.load_alignments(path)
        .mark_duplicates()
        .realign_indels()
        .recalibrate_base_qualities()
    )
    out = str(tmp_path / "out.adam")
    stats = transform_streamed(path, out, window_reads=1024)
    assert stats["n_reads"] == 8192
    back = context.load_alignments(out)
    _assert_equal(mono, back)


def test_streamed_boundary_duplicates_and_targets(tmp_path):
    """Duplicate groups and an indel target engineered to straddle a
    window edge (window_reads=8): the global resolves must see them
    whole."""
    from adam_tpu.io.sam import SamHeader, write_sam
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.models.dictionaries import (
        RecordGroup, RecordGroupDictionary, SequenceDictionary,
        SequenceRecord,
    )

    sd = SequenceDictionary((SequenceRecord("chr1", 100000),))
    rgd = RecordGroupDictionary((RecordGroup("rg1", library="lib1"),))
    recs = []
    # 12 duplicate fragments at one position -> rows 0..11 span windows
    # 0 and 1 (window_reads=8); the winner (highest quality) is in
    # window 1, so cross-window score comparison is exercised
    for i in range(12):
        phred = 30 if i == 9 else 20
        recs.append(dict(
            name=f"frag{i}", flags=0, contig_idx=0, start=500, mapq=60,
            cigar="20M", seq="ACGTACGTACGTACGTACGT", qual=chr(33 + phred) * 20,
            read_group_idx=0, attrs="MD:Z:20",
        ))
    # an insertion-carrying read just before the window-2 edge plus
    # overlapping mismatch-free reads after the edge: a realignment
    # target whose reads live in two windows
    recs.append(dict(
        name="indel", flags=0, contig_idx=0, start=600, mapq=60,
        cigar="10M2I8M", seq="AAAAAAAAAACCAAAAAAAA", qual="I" * 20,
        read_group_idx=0, attrs="MD:Z:18",
    ))
    for i in range(8):
        recs.append(dict(
            name=f"cover{i}", flags=0, contig_idx=0, start=598 + i, mapq=60,
            cigar="20M", seq="A" * 20, qual="I" * 20,
            read_group_idx=0, attrs="MD:Z:20",
        ))
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=sd, read_groups=rgd)
    path = str(tmp_path / "in.sam")
    write_sam(path, batch, side, header)

    mono = (
        context.load_alignments(path)
        .mark_duplicates()
        .realign_indels()
        .recalibrate_base_qualities()
    )
    out = str(tmp_path / "out.adam")
    transform_streamed(path, out, window_reads=8)
    back = context.load_alignments(out)
    _assert_equal(mono, back)

    # sanity on the duplicate semantics themselves: exactly 11 of the 12
    # fragments marked, winner unmarked
    b = back.compact()
    bb = b.batch.to_numpy()
    dup = (np.asarray(bb.flags) & schema.FLAG_DUPLICATE) != 0
    marks = {b.sidecar.names[i]: bool(dup[i]) for i in range(bb.n_rows)}
    assert not marks["frag9"]
    assert sum(marks[f"frag{i}"] for i in range(12)) == 11


def test_streamed_stage_toggles(tmp_path):
    """Each stage can be disabled independently (the CLI flag set)."""
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 2048, 100)
    mono = context.load_alignments(path).mark_duplicates()
    out = str(tmp_path / "out.adam")
    transform_streamed(
        path, out, window_reads=512, recalibrate=False, realign=False
    )
    back = context.load_alignments(out)
    _assert_equal(mono, back)


def test_streamed_tuning_flags_and_dump_observations(tmp_path):
    """The realign tuning knobs thread through (a prohibitive LOD
    threshold suppresses all realignment) and -dump_observations writes
    the merged observation table CSV."""
    from make_wgs_sam import make_wgs

    path = str(tmp_path / "in.sam")
    make_wgs(path, 2048, 100, n_contigs=1, contig_len=30_000)
    obs = str(tmp_path / "obs.csv")
    out1 = str(tmp_path / "strict.adam")
    transform_streamed(
        path, out1, window_reads=512,
        lod_threshold=1e12, dump_observations=obs,
    )
    assert open(obs).read().startswith("ReadGroup,")
    strict = context.load_alignments(out1)
    b1 = strict.batch.to_numpy()
    # nothing clears the absurd LOD bar: no read gets the +10 mapq
    base_mapq = int(np.asarray(b1.mapq)[np.asarray(b1.valid)].max())
    out2 = str(tmp_path / "default.adam")
    transform_streamed(path, out2, window_reads=512)
    b2 = context.load_alignments(out2).batch.to_numpy()
    assert int(np.asarray(b2.mapq).max()) == base_mapq + 10  # default realigns
    assert int(np.asarray(b1.mapq).max()) == base_mapq
