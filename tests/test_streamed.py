"""Streamed overlapped transform: equality with the monolithic path.

The pipeline contract (pipelines/streamed.py) is that window edges are
invisible: duplicate groups, BQSR statistics and realignment targets
that span two ingest windows must resolve exactly as in one batch — the
same boundary-correctness the sharded path needs
(rdd/read/MarkDuplicates.scala:66-128, GenomicPartitioners.scala:63-85).
"""

import os
import sys

import numpy as np
import pytest

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.strings import StringColumn
from adam_tpu.io import context
from adam_tpu.pipelines.streamed import transform_streamed

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


def _row_table(ds):
    """Window-order-independent view: rows keyed by (name, flags)."""
    d = ds.compact()
    b = d.batch.to_numpy()
    names = StringColumn.of(d.sidecar.names).to_fixed_bytes().astype("S64")
    order = np.lexsort((np.asarray(b.flags), names))
    cols = {
        f: np.asarray(getattr(b, f))[order]
        for f in ["flags", "start", "end", "mapq", "lengths", "contig_idx",
                  "cigar_n"]
    }
    cols["names"] = names[order]
    L = b.lmax
    cols["quals"] = np.asarray(b.quals)[order][:, :L]
    side = d.sidecar
    cols["md"] = [side.md[i] for i in order]
    cols["attrs"] = [side.attrs[i] for i in order]
    cols["oq"] = [side.orig_quals[i] for i in order]
    return cols


def _assert_equal(mono, streamed):
    a, b = _row_table(mono), _row_table(streamed)
    np.testing.assert_array_equal(a["names"], b["names"])
    for f in ["flags", "start", "end", "mapq", "lengths", "contig_idx",
              "cigar_n"]:
        np.testing.assert_array_equal(a[f], b[f], err_msg=f)
    L = min(a["quals"].shape[1], b["quals"].shape[1])
    np.testing.assert_array_equal(a["quals"][:, :L], b["quals"][:, :L])
    assert a["md"] == b["md"]
    assert a["attrs"] == b["attrs"]
    assert a["oq"] == b["oq"]


def test_streamed_matches_monolithic(tmp_path):
    """8 windows of a synthetic WGS-shaped file: identical output rows
    (flags incl. duplicate marks, recalibrated quals, realigned cigars,
    MD/OQ/attrs) vs load-then-stage-by-stage."""
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 8192, 100)
    mono = (
        context.load_alignments(path)
        .mark_duplicates()
        .realign_indels()
        .recalibrate_base_qualities()
    )
    out = str(tmp_path / "out.adam")
    stats = transform_streamed(path, out, window_reads=1024)
    assert stats["n_reads"] == 8192
    back = context.load_alignments(out)
    _assert_equal(mono, back)


def test_streamed_boundary_duplicates_and_targets(tmp_path):
    """Duplicate groups and an indel target engineered to straddle a
    window edge (window_reads=8): the global resolves must see them
    whole."""
    from adam_tpu.io.sam import SamHeader, write_sam
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.models.dictionaries import (
        RecordGroup, RecordGroupDictionary, SequenceDictionary,
        SequenceRecord,
    )

    sd = SequenceDictionary((SequenceRecord("chr1", 100000),))
    rgd = RecordGroupDictionary((RecordGroup("rg1", library="lib1"),))
    recs = []
    # 12 duplicate fragments at one position -> rows 0..11 span windows
    # 0 and 1 (window_reads=8); the winner (highest quality) is in
    # window 1, so cross-window score comparison is exercised
    for i in range(12):
        phred = 30 if i == 9 else 20
        recs.append(dict(
            name=f"frag{i}", flags=0, contig_idx=0, start=500, mapq=60,
            cigar="20M", seq="ACGTACGTACGTACGTACGT", qual=chr(33 + phred) * 20,
            read_group_idx=0, attrs="MD:Z:20",
        ))
    # an insertion-carrying read just before the window-2 edge plus
    # overlapping mismatch-free reads after the edge: a realignment
    # target whose reads live in two windows
    recs.append(dict(
        name="indel", flags=0, contig_idx=0, start=600, mapq=60,
        cigar="10M2I8M", seq="AAAAAAAAAACCAAAAAAAA", qual="I" * 20,
        read_group_idx=0, attrs="MD:Z:18",
    ))
    for i in range(8):
        recs.append(dict(
            name=f"cover{i}", flags=0, contig_idx=0, start=598 + i, mapq=60,
            cigar="20M", seq="A" * 20, qual="I" * 20,
            read_group_idx=0, attrs="MD:Z:20",
        ))
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=sd, read_groups=rgd)
    path = str(tmp_path / "in.sam")
    write_sam(path, batch, side, header)

    mono = (
        context.load_alignments(path)
        .mark_duplicates()
        .realign_indels()
        .recalibrate_base_qualities()
    )
    out = str(tmp_path / "out.adam")
    transform_streamed(path, out, window_reads=8)
    back = context.load_alignments(out)
    _assert_equal(mono, back)

    # sanity on the duplicate semantics themselves: exactly 11 of the 12
    # fragments marked, winner unmarked
    b = back.compact()
    bb = b.batch.to_numpy()
    dup = (np.asarray(bb.flags) & schema.FLAG_DUPLICATE) != 0
    marks = {b.sidecar.names[i]: bool(dup[i]) for i in range(bb.n_rows)}
    assert not marks["frag9"]
    assert sum(marks[f"frag{i}"] for i in range(12)) == 11


def test_streamed_stage_toggles(tmp_path):
    """Each stage can be disabled independently (the CLI flag set)."""
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 2048, 100)
    mono = context.load_alignments(path).mark_duplicates()
    out = str(tmp_path / "out.adam")
    transform_streamed(
        path, out, window_reads=512, recalibrate=False, realign=False
    )
    back = context.load_alignments(out)
    _assert_equal(mono, back)


# ---------------------------------------------------------------------------
# Durable window-granular resume (docs/ROBUSTNESS.md; --run-dir/--resume)
# ---------------------------------------------------------------------------
def _parts_hash(out_dir):
    import hashlib

    return {
        f: hashlib.sha256(
            open(os.path.join(out_dir, f), "rb").read()
        ).hexdigest()
        for f in os.listdir(out_dir) if f.startswith("part-")
    }


def test_streamed_journal_resume_skips_completed_windows(tmp_path):
    """A journaled run resumes: a full resume skips every window, a
    resume after two parts vanish rewrites exactly those two —
    byte-identical to the journal-free run either way."""
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 2048, 100)
    clean = str(tmp_path / "clean.adam")
    transform_streamed(path, clean, window_reads=256)
    baseline = _parts_hash(clean)

    out, rd = str(tmp_path / "j.adam"), str(tmp_path / "run")
    s1 = transform_streamed(path, out, window_reads=256, run_dir=rd)
    assert s1["windows_resumed"] == 0
    assert _parts_hash(out) == baseline
    # the journal artifacts exist: window map + obs sidecars + table
    assert os.path.isfile(os.path.join(rd, "JOURNAL.json"))
    assert os.path.isfile(os.path.join(rd, "table.npz"))
    assert os.listdir(os.path.join(rd, "obs"))

    s2 = transform_streamed(path, out, window_reads=256, run_dir=rd,
                            resume=True)
    assert s2["windows_fresh"] == 0 and s2["windows_resumed"] > 0
    assert _parts_hash(out) == baseline

    # journaled-but-deleted parts degrade to "incomplete", never a hole
    os.unlink(os.path.join(out, "part-r-00001.parquet"))
    os.unlink(os.path.join(out, "part-r-00004.parquet"))
    s3 = transform_streamed(path, out, window_reads=256, run_dir=rd,
                            resume=True)
    assert s3["windows_fresh"] == 2
    assert _parts_hash(out) == baseline


_KILL_DRIVER = (
    "import sys\n"
    "try:\n"
    "    import jax, jax._src.xla_bridge as xb\n"
    "    xb._backend_factories.pop('axon', None)\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "except Exception: pass\n"
    "from adam_tpu.pipelines.streamed import transform_streamed\n"
    "transform_streamed(sys.argv[1], sys.argv[2], window_reads=256,\n"
    "                   run_dir=sys.argv[3], resume=sys.argv[4] == '1')\n"
)

#: (phase, arrival offset) — one SIGKILL at each phase boundary the
#: proc.kill fault point exposes (docs/ROBUSTNESS.md)
_KILL_MATRIX = [
    ("ingest", 3), ("pass_a", 4), ("pass_b", 2), ("barrier2", 0),
    ("pass_c", 2), ("write", 1),
]


@pytest.mark.parametrize("phase,after", _KILL_MATRIX,
                         ids=[p for p, _ in _KILL_MATRIX])
def test_streamed_sigkill_then_resume_bit_identical(
    tmp_path_factory, kill_resume_input, phase, after
):
    """SIGKILL (a real host death via the proc.kill fault point) at
    each phase boundary, then --resume: the completed output must be
    byte-identical to the uninterrupted run."""
    import signal
    import subprocess

    path, baseline = kill_resume_input
    d = tmp_path_factory.mktemp(f"kill_{phase}")
    out, rd = str(d / "out.adam"), str(d / "run")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # host backend: the machinery under test is the journal, and a
        # subprocess chip probe would only slow both runs down
        "ADAM_TPU_BQSR_BACKEND": "numpy",
        "ADAM_TPU_FAULTS":
            f"proc.kill=kill,device={phase},after={after},times=1",
    })
    cwd = os.path.join(os.path.dirname(__file__), "..")
    rc = subprocess.run(
        [sys.executable, "-c", _KILL_DRIVER, path, out, rd, "0"],
        env=env, cwd=cwd,
    ).returncode
    assert rc == -signal.SIGKILL, f"{phase}: expected SIGKILL, got {rc}"
    env.pop("ADAM_TPU_FAULTS")
    rc = subprocess.run(
        [sys.executable, "-c", _KILL_DRIVER, path, out, rd, "1"],
        env=env, cwd=cwd,
    ).returncode
    assert rc == 0, f"{phase}: resume exited {rc}"
    assert _parts_hash(out) == baseline, f"{phase}: resumed output differs"
    # crash consistency held throughout: no staging residue
    assert not [f for f in os.listdir(out) if f.endswith(".tmp")]
    assert not os.path.isdir(os.path.join(out, "_temporary"))


@pytest.fixture(scope="module")
def kill_resume_input(tmp_path_factory):
    """Shared input + uninterrupted-run baseline for the SIGKILL matrix
    (one numpy-backend run, matching the subprocess drivers)."""
    from make_synth_sam import make_sam

    d = tmp_path_factory.mktemp("kill_resume")
    path = str(d / "in.sam")
    make_sam(path, 2048, 100)
    clean = str(d / "clean.adam")
    os.environ["ADAM_TPU_BQSR_BACKEND"] = "numpy"
    try:
        transform_streamed(path, clean, window_reads=256)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    return path, _parts_hash(clean)


def test_streamed_resume_refuses_changed_input_and_flags(tmp_path):
    """A resume whose input bytes or flag composition differ from the
    journal's fingerprint restarts clean — the output must equal a
    fresh run of the NEW configuration, with no stale parts mixed in."""
    from make_synth_sam import make_sam

    pA, pB = str(tmp_path / "a.sam"), str(tmp_path / "b.sam")
    make_sam(pA, 1024, 100)
    make_sam(pB, 1536, 100)
    out, rd = str(tmp_path / "out.adam"), str(tmp_path / "run")
    transform_streamed(pA, out, window_reads=256, run_dir=rd)

    # changed input content: refused, restarted, equals clean run of B
    s = transform_streamed(pB, out, window_reads=256, run_dir=rd,
                           resume=True)
    assert s["windows_resumed"] == 0
    clean_b = str(tmp_path / "cleanB.adam")
    transform_streamed(pB, clean_b, window_reads=256)
    assert _parts_hash(out) == _parts_hash(clean_b)

    # changed window plan: refused again (the part layout would differ)
    s = transform_streamed(pB, out, window_reads=512, run_dir=rd,
                           resume=True)
    assert s["windows_resumed"] == 0
    # changed stage composition: ditto
    s = transform_streamed(pB, out, window_reads=512, run_dir=rd,
                           resume=True, realign=False)
    assert s["windows_resumed"] == 0


def test_streamed_resume_tolerates_torn_journal(tmp_path):
    """A corrupt/torn journal (crashed writer, disk hiccup) costs a
    clean restart, not a crash and never trust."""
    from make_synth_sam import make_sam

    path = str(tmp_path / "in.sam")
    make_sam(path, 1024, 100)
    out, rd = str(tmp_path / "out.adam"), str(tmp_path / "run")
    transform_streamed(path, out, window_reads=256, run_dir=rd)
    baseline = _parts_hash(out)
    with open(os.path.join(rd, "JOURNAL.json"), "w") as fh:
        fh.write('{"schema": "adam_tpu.run_journal/1", "windows": TORN')
    s = transform_streamed(path, out, window_reads=256, run_dir=rd,
                           resume=True)
    assert s["windows_resumed"] == 0 and s["windows_fresh"] > 0
    assert _parts_hash(out) == baseline


def test_streamed_tuning_flags_and_dump_observations(tmp_path):
    """The realign tuning knobs thread through (a prohibitive LOD
    threshold suppresses all realignment) and -dump_observations writes
    the merged observation table CSV."""
    from make_wgs_sam import make_wgs

    path = str(tmp_path / "in.sam")
    make_wgs(path, 2048, 100, n_contigs=1, contig_len=30_000)
    obs = str(tmp_path / "obs.csv")
    out1 = str(tmp_path / "strict.adam")
    transform_streamed(
        path, out1, window_reads=512,
        lod_threshold=1e12, dump_observations=obs,
    )
    assert open(obs).read().startswith("ReadGroup,")
    strict = context.load_alignments(out1)
    b1 = strict.batch.to_numpy()
    # nothing clears the absurd LOD bar: no read gets the +10 mapq
    base_mapq = int(np.asarray(b1.mapq)[np.asarray(b1.valid)].max())
    out2 = str(tmp_path / "default.adam")
    transform_streamed(path, out2, window_reads=512)
    b2 = context.load_alignments(out2).batch.to_numpy()
    assert int(np.asarray(b2.mapq).max()) == base_mapq + 10  # default realigns
    assert int(np.asarray(b1.mapq).max()) == base_mapq
