"""Trim pipeline tests — mirrors the reference TrimReadsSuite
(adam-core/src/test/scala/.../rdd/read/correction/TrimReadsSuite.scala)."""

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.io import context as ctx
from adam_tpu.io.sam import SamHeader
from adam_tpu.pipelines import trim


def _trim_cigar_str(cigar, ts, te, start, end):
    ops, lens, n = schema.encode_cigar(cigar, 8)
    elems, s, e, _af, _ab = trim.trim_cigar(ops, lens, n, ts, te, start, end)
    return (
        "".join(f"{ln}{schema.CIGAR_CHARS[op]}" for ln, op in elems),
        s,
        e,
    )


def test_trim_md_tags():
    assert trim.trim_md_tag("10", 2, 0) == "8"
    assert trim.trim_md_tag("2A10", 4, 0) == "9"
    assert trim.trim_md_tag("0C10C1", 1, 2) == "10"
    assert trim.trim_md_tag("1^AC3", 2, 0) == "2"
    assert trim.trim_md_tag("3^AC1", 0, 2) == "2"
    assert trim.trim_md_tag("2A0C0", 3, 0) == "0C0"
    assert trim.trim_md_tag("2A0C0", 0, 1) == "2A0"


def test_trim_cigar_clips_and_matches():
    assert _trim_cigar_str("2S10M", 1, 0, 0, 10) == ("1H1S10M", 0, 10)
    assert _trim_cigar_str("10M3S", 0, 2, 0, 10) == ("10M1S2H", 0, 10)
    assert _trim_cigar_str("2S10M3S", 1, 2, 0, 10) == ("1H1S10M1S2H", 0, 10)
    assert _trim_cigar_str("2S10M", 2, 0, 0, 10) == ("2H10M", 0, 10)
    assert _trim_cigar_str("10M3S", 0, 3, 0, 10) == ("10M3H", 0, 10)
    assert _trim_cigar_str("2S10M3S", 2, 3, 0, 10) == ("2H10M3H", 0, 10)
    assert _trim_cigar_str("2S10M", 3, 0, 0, 10) == ("3H9M", 1, 10)
    assert _trim_cigar_str("10M3S", 0, 4, 0, 10) == ("9M4H", 0, 9)
    assert _trim_cigar_str("2S10M3S", 3, 4, 0, 10) == ("3H8M4H", 1, 9)


def test_trim_cigar_indels():
    assert _trim_cigar_str("2S2M2D4M", 5, 0, 0, 8) == ("5H3M", 5, 8)
    assert _trim_cigar_str("4M1D1M", 0, 3, 0, 6) == ("2M3H", 0, 2)
    assert _trim_cigar_str("2S2M2N4M", 5, 0, 0, 8) == ("5H3M", 5, 8)
    assert _trim_cigar_str("4M1N1M", 0, 3, 0, 6) == ("2M3H", 0, 2)
    assert _trim_cigar_str("2M2I10M", 3, 0, 0, 12) == ("3H1I10M", 2, 12)
    assert _trim_cigar_str("10M3I1M", 0, 3, 0, 11) == ("10M1I3H", 0, 10)


def _dataset(records):
    batch, side = pack_reads(records)
    return AlignmentDataset(batch, side, SamHeader())


def _read(seq, qual, cigar="*", start=-1, **kw):
    return dict(
        name="r", flags=0, seq=seq, qual=qual, cigar=cigar, start=start, **kw
    )


def test_trim_read_with_cigar():
    ds = _dataset(
        [
            _read("ACTCGCCCACTCAAA", "##/9:::::::::##", "2S11M2S", 5),
            _read("ACTCGCCCACTCAAA", "##/9:::::::::##", "15M", 5),
        ]
    )
    t2 = trim.trim_reads(ds, 2, 2)
    b = t2.batch.to_numpy()
    assert schema.decode_bases(b.bases[0], int(b.lengths[0])) == "TCGCCCACTCA"
    assert schema.decode_quals(b.quals[0], int(b.lengths[0])) == "/9:::::::::"
    assert int(b.start[0]) == 5 and int(b.end[0]) == 16
    assert (
        schema.decode_cigar(b.cigar_ops[0], b.cigar_lens[0], int(b.cigar_n[0]))
        == "2H11M2H"
    )
    assert t2.sidecar.trimmed_from_start[0] == 2
    assert t2.sidecar.trimmed_from_end[0] == 2

    t3 = trim.trim_reads(ds, 4, 3)
    b = t3.batch.to_numpy()
    assert schema.decode_bases(b.bases[1], int(b.lengths[1])) == "GCCCACTC"
    assert int(b.start[1]) == 9 and int(b.end[1]) == 17
    assert (
        schema.decode_cigar(b.cigar_ops[1], b.cigar_lens[1], int(b.cigar_n[1]))
        == "4H8M3H"
    )


def test_trim_batch_sequential():
    seqs = ["AACTCGACGCTTT", "AACTCCCTGCTTT", "AACTCATAGCTTT",
            "AACTCCCAGCTTT", "AACTCGGAGCTTT"]
    ds = _dataset([_read(s, "##::::::::$$$") for s in seqs])
    front = trim.trim_reads(ds, 2, 0)
    b = front.batch.to_numpy()
    for i in range(5):
        s = schema.decode_bases(b.bases[i], int(b.lengths[i]))
        q = schema.decode_quals(b.quals[i], int(b.lengths[i]))
        assert len(s) == 11 and len(q) == 11
        assert s.startswith("CT") and s.endswith("TTT")
        assert q.startswith("::") and q.endswith("$$$")
        assert front.sidecar.trimmed_from_start[i] == 2
        assert front.sidecar.trimmed_from_end[i] == 0

    both = trim.trim_reads(front, 0, 3)
    b = both.batch.to_numpy()
    for i in range(5):
        s = schema.decode_bases(b.bases[i], int(b.lengths[i]))
        assert len(s) == 8
        assert s.startswith("CT") and s.endswith("GC")
        assert schema.decode_quals(b.quals[i], int(b.lengths[i])) == "::::::::"
        assert both.sidecar.trimmed_from_start[i] == 2
        assert both.sidecar.trimmed_from_end[i] == 3


def test_adaptive_trim_bqsr1(ref_resources):
    """Threshold Q10 on bqsr1.sam trims 5 bases off each end
    (TrimReadsSuite 'adaptively trim reads')."""
    ds = ctx.load_alignments(str(ref_resources / "bqsr1.sam"))
    trimmed = trim.trim_low_quality_read_groups(ds, 10)
    assert all(v == 5 for v in trimmed.sidecar.trimmed_from_start)
    assert all(v == 5 for v in trimmed.sidecar.trimmed_from_end)


def test_trim_api_roundtrip(tmp_path):
    ds = _dataset([_read("ACGTACGTAC", "IIIIIIIIII", "10M", 3)])
    t = ds.trim_reads(1, 1)
    out = tmp_path / "t.adam"
    t.save(str(out))
    ds2 = AlignmentDataset.load(str(out))
    assert ds2.sidecar.trimmed_from_start == [1]
    assert ds2.sidecar.trimmed_from_end == [1]
    b = ds2.batch.to_numpy()
    assert int(b.lengths[0]) == 8


class TestReviewRegressions:
    def test_existing_hard_clips_preserved(self):
        """H consumes no read bases: 5H95M trimmed by 2 gives 7H93M."""
        assert _trim_cigar_str("5H95M", 2, 0, 100, 195) == ("7H93M", 102, 195)
        assert _trim_cigar_str("95M5H", 0, 2, 100, 195) == ("93M7H", 100, 193)

    def test_soft_clip_trim_leaves_md_alone(self):
        """Trimming only soft clips must not touch the MD tag."""
        ds = _dataset([
            _read("ACGTACGTACGT", "IIIIIIIIIIII", "2S10M", 50, md="10"),
        ])
        t = trim.trim_reads(ds, 2, 0)
        assert t.sidecar.md[0] == "10"
        b = t.batch.to_numpy()
        assert int(b.start[0]) == 50
        assert (
            schema.decode_cigar(b.cigar_ops[0], b.cigar_lens[0],
                                int(b.cigar_n[0]))
            == "2H10M"
        )

    def test_aligned_trim_still_trims_md(self):
        ds = _dataset([
            _read("ACGTACGTACGT", "IIIIIIIIIIII", "12M", 50, md="12"),
        ])
        t = trim.trim_reads(ds, 2, 1)
        assert t.sidecar.md[0] == "9"

    def test_wigfix_skips_track_and_comment_lines(self):
        from adam_tpu.io.features import wigfix_to_bed_lines

        rows = list(wigfix_to_bed_lines([
            "track type=wiggle_0 name=x",
            "# a comment",
            "fixedStep chrom=chr1 start=5 step=1",
            "1.5",
        ]))
        assert len(rows) == 1 and rows[0].split("\t")[:3] == ["chr1", "4", "5"]

    def test_flank_fragments_skips_gaps(self):
        import numpy as np

        from adam_tpu.formats.fragments import (
            FragmentBatch,
            count_contig_kmers,
            flank_fragments,
        )

        fb = FragmentBatch.from_sequences([(0, "ACGTACGTAA")], 4)
        # drop the middle fragment -> gap between [0,4) and [8,10)
        fb = fb.take(np.array([0, 2])).to_numpy()
        flanked = flank_fragments(fb, 2)
        assert list(np.asarray(flanked.lengths)) == [4, 2]
        counts = count_contig_kmers(fb, 3)
        assert counts == {"ACG": 1, "CGT": 1}  # no fabricated GTA/TAA bridge
