"""Continuous cross-job window batching + per-tenant quotas
(adam_tpu/serve/batching.py + quota.py; docs/SERVING.md "Continuous
batching & quotas").

The pipeline-backed tests run the REAL streamed transform with the
device kernels on the CPU jax backend and byte-compare every batched
job's output against a solo fault-free baseline — the coalescer's core
contract is that fusing cross-job dispatches changes how work reaches
the device, never the bytes.
"""

import hashlib
import os
import sys
import time

import numpy as np
import pytest

from adam_tpu.serve import (
    DONE,
    QUARANTINED,
    Admitted,
    Busy,
    JobScheduler,
    JobSpec,
    QuotaManager,
    WeightedInterleaver,
)
from adam_tpu.serve import batching as batching_mod
from adam_tpu.serve.batching import CoalesceError, WindowCoalescer
from adam_tpu.serve.quota import (
    Budget,
    parse_quota_spec,
    parse_size,
    rate_retry_hint,
)
from adam_tpu.utils import faults
from adam_tpu.utils import telemetry as tele

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parts_hash(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(d)) if f.startswith("part-")
    }


@pytest.fixture(scope="module")
def batch_input(tmp_path_factory):
    """One synthetic input + its solo fault-free baseline (numpy
    backend — valid for the device-batched runs by backend parity)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from make_synth_sam import make_sam

    work = tmp_path_factory.mktemp("batching")
    path = str(work / "in.sam")
    make_sam(path, 2048, 100)
    solo = str(work / "solo.adam")
    os.environ["ADAM_TPU_BQSR_BACKEND"] = "numpy"
    try:
        from adam_tpu.pipelines.streamed import transform_streamed

        transform_streamed(path, solo, window_reads=512)
    finally:
        os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
    return {"input": path, "baseline": _parts_hash(solo)}


@pytest.fixture()
def device_backend(monkeypatch):
    """The coalescer only engages on the device backend (CPU jax)."""
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "device")
    monkeypatch.setenv("ADAM_TPU_RETRY_BACKOFF_S", "0.001")


def _spec(jid, batch_input, tmp_path, **kw):
    return JobSpec(
        job_id=jid, input=batch_input["input"],
        output=str(tmp_path / f"{jid}.adam"), window_reads=512, **kw,
    )


def _batch_counters():
    c, g = tele.TRACE.counters_and_gauges()
    return c, g


# ---------------------------------------------------------------------------
# Knob parsing + quota grammar units
# ---------------------------------------------------------------------------
def test_batch_wait_ms_parsing(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_BATCH_WAIT_MS", raising=False)
    assert batching_mod.batch_wait_ms() == batching_mod.DEFAULT_BATCH_WAIT_MS
    monkeypatch.setenv("ADAM_TPU_BATCH_WAIT_MS", "7.5")
    assert batching_mod.batch_wait_ms() == 7.5
    monkeypatch.setenv("ADAM_TPU_BATCH_WAIT_MS", "0")
    assert batching_mod.batch_wait_ms() == 0.0
    # the tuning-var contract: a typo degrades to the default
    monkeypatch.setenv("ADAM_TPU_BATCH_WAIT_MS", "soon")
    assert batching_mod.batch_wait_ms() == batching_mod.DEFAULT_BATCH_WAIT_MS


def test_batching_toggle(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_BATCH", raising=False)
    assert batching_mod.batching_enabled() is False
    monkeypatch.setenv("ADAM_TPU_BATCH", "1")
    assert batching_mod.batching_enabled() is True
    monkeypatch.setenv("ADAM_TPU_BATCH", "off")
    assert batching_mod.batching_enabled() is False


def test_parse_size_suffixes():
    assert parse_size("512") == 512
    assert parse_size("4K") == 4096
    assert parse_size("2m") == 2 << 20
    assert parse_size("1G") == 1 << 30


def test_quota_grammar():
    budgets = parse_quota_spec(
        "tenantA:bytes=512M,compute=10s;tenantB:bytes=2G;*:bytes=1G"
    )
    assert budgets["tenantA"] == Budget(bytes=512 << 20, compute_s=10.0)
    assert budgets["tenantB"] == Budget(bytes=2 << 30, compute_s=None)
    assert budgets["*"].bytes == 1 << 30
    # malformed clauses are skipped, never fatal (tuning-var contract)
    assert parse_quota_spec("oops;a:bytes=nope;b:bytes=4K") == {
        "b": Budget(bytes=4096)
    }
    qm = QuotaManager("b:bytes=4K")
    assert qm.budget_for("b").bytes == 4096
    assert qm.budget_for("unknown").limited is False
    assert qm.enforcing


def test_quota_rolling_window_and_retry_after():
    clock = {"t": 1000.0}
    qm = QuotaManager("g:bytes=100", window_s=60.0,
                      clock=lambda: clock["t"])
    assert qm.check("g") is None
    qm.charge("g", nbytes=80)
    clock["t"] += 10
    qm.charge("g", nbytes=80)
    exceeded = qm.check("g")
    assert exceeded is not None and exceeded.resource == "bytes"
    assert exceeded.used == 160 and exceeded.budget == 100
    # deficit 60 frees when the FIRST charge (80 bytes, at t=1000)
    # ages out of the window: 1000 + 60 - 1010 = 50 s
    assert exceeded.retry_after_s == 50
    # advance past that expiry: admissible again (the resubmit leg)
    clock["t"] = 1061.0
    assert qm.check("g") is None
    assert qm.consumed("g") == (80, 0.0)
    # compute budgets enforce the same way
    qm2 = QuotaManager("c:compute=1s", window_s=30.0,
                       clock=lambda: clock["t"])
    qm2.charge("c", compute_s=2.0)
    got = qm2.check("c")
    assert got is not None and got.resource == "compute_s"
    st = qm2.status()["tenants"]["c"]
    assert st["compute_s_used"] == 2.0 and st["budget_compute_s"] == 1.0


def test_rate_retry_hint_bytes_per_grant():
    # 10 grants of 1000 bytes over 9 seconds -> ~1111 B/s; a deficit
    # of 11111 bytes needs ~10 s
    recs = [(float(t), 1000) for t in range(10)]
    hint = rate_retry_hint(11111, recs, now=9.0)
    assert hint == 10
    # no sized grants (pre-sizes ring) -> no estimate
    assert rate_retry_hint(1000, [(1.0, 0), (2.0, 0)]) is None
    assert rate_retry_hint(0, recs) is None


def test_grant_ring_records_sizes():
    """The satellite fix: the ring carries sizes beside timestamps so
    the quota leg can reason in bytes-per-grant."""
    inter = WeightedInterleaver()
    inter.register("j", tenant="T")
    pace = inter.pacer("j")
    pace("pass_a", 0, 4096)
    pace("pass_c", 0)  # callers that predate sizes record 0
    recs = inter.grant_records()
    assert [s for _, s in recs] == [4096, 0]
    assert inter.grant_history() == ["j", "j"]
    assert len(inter.grant_times()) == 2
    assert inter.tenant_clock("T") is not None
    assert inter.tenant_clock("unknown") is None


# ---------------------------------------------------------------------------
# Coalescer mechanics (white-box, no pipeline)
# ---------------------------------------------------------------------------
def test_wfq_rank_orders_by_tenant_clock():
    inter = WeightedInterleaver()
    inter.register("a", tenant="A", weight=1.0)
    inter.register("b", tenant="B", weight=1.0)
    # advance tenant A's clock: B becomes the more underserved tenant
    inter.turn("a")
    inter.turn("a")
    coal = WindowCoalescer(wait_ms=0, interleaver=inter)
    try:
        ta = batching_mod._Ticket("observe", ("observe", 128), "a",
                                  "A", 0, 1, 512, 1024, 128, {})
        tb = batching_mod._Ticket("observe", ("observe", 128), "b",
                                  "B", 0, 2, 512, 1024, 128, {})
        grp = [ta, tb]
        grp.sort(key=coal._wfq_rank)
        # B's clock is behind A's -> B's block leads the fused grid
        assert [t.job for t in grp] == ["b", "a"]
    finally:
        coal.stop()


def test_flush_conditions():
    """A group flushes early the moment every registered job is
    represented; otherwise it waits out the bounded delay."""
    coal = WindowCoalescer(wait_ms=10_000)
    try:
        coal.client("j1")
        coal.client("j2")
        t = batching_mod._Ticket("observe", ("observe", 128), "j1",
                                 "default", 0, 1, 512, 1024, 128, {})
        with coal._lock:
            coal._pending.append(t)
            # only j1 present, deadline far away: not ripe
            assert coal._take_group_locked() is None
        t2 = batching_mod._Ticket("observe", ("observe", 128), "j2",
                                  "default", 0, 2, 512, 1024, 128, {})
        with coal._lock:
            coal._pending.append(t2)
            grp = coal._take_group_locked()
            assert grp is not None and len(grp) == 2
            assert coal._pending == []
        # a deregistered job no longer blocks the flush
        t3 = batching_mod._Ticket("observe", ("observe", 128), "j1",
                                  "default", 1, 3, 512, 1024, 128, {})
        coal.deregister("j2")
        with coal._lock:
            coal._pending.append(t3)
            grp = coal._take_group_locked()
            assert grp is not None and [x.job for x in grp] == ["j1"]
    finally:
        coal.stop()


def test_bounded_delay_flush_and_markdup_parity(device_backend,
                                                batch_input):
    """A lone job's ticket flushes after ADAM_TPU_BATCH_WAIT_MS even
    though a second registered job never shows up — and the fused
    markdup columns are bitwise the solo dispatch's."""
    from adam_tpu.io import sam as sam_io
    from adam_tpu.pipelines.markdup import markdup_columns_device

    batch, _side, _hdr = next(
        sam_io.iter_sam_batches(batch_input["input"], batch_reads=512)
    )
    solo_five, solo_score = markdup_columns_device(batch)
    coal = WindowCoalescer(wait_ms=100.0)
    try:
        client = coal.client("lone")
        coal.client("never-submits")
        t0 = time.monotonic()
        fut = client.submit_markdup(0, batch)
        five, score = fut.result(timeout=60)
        waited = time.monotonic() - t0
        # the bounded delay actually bounded: the group waited for the
        # absent job, then flushed (generous ceiling for slow CI)
        assert 0.08 <= waited < 30.0, waited
        np.testing.assert_array_equal(five, np.asarray(solo_five))
        np.testing.assert_array_equal(score, np.asarray(solo_score))
    finally:
        coal.stop()
    # a stopped coalescer refuses new tickets (callers fall back solo)
    with pytest.raises(CoalesceError):
        coal._submit("markdup", ("markdup", 1, 1), "x", "t", 0, 1, 1,
                     1, {})


def test_two_job_fused_markdup_slices_are_solo(device_backend,
                                               batch_input):
    """Two jobs' windows fuse into ONE dispatch; each job's row slice
    is bitwise its solo columns (the per-job slice parity axiom the
    pipeline-level byte-identity rests on)."""
    from adam_tpu.io import sam as sam_io
    from adam_tpu.pipelines.markdup import markdup_columns_device

    it = sam_io.iter_sam_batches(batch_input["input"], batch_reads=512)
    b1 = next(it)[0]
    b2 = next(it)[0]
    solo = [markdup_columns_device(b) for b in (b1, b2)]
    tele.TRACE.recording = True
    before, _ = _batch_counters()
    coal = WindowCoalescer(wait_ms=2000.0)
    try:
        c1 = coal.client("j1")
        c2 = coal.client("j2")
        f1 = c1.submit_markdup(0, b1)
        f2 = c2.submit_markdup(0, b2)
        t0 = time.monotonic()
        r1 = f1.result(timeout=120)
        r2 = f2.result(timeout=120)
        # both jobs present -> flushed well before the 2 s delay
        assert time.monotonic() - t0 < 60
        for (five, score), (sf, ss) in zip((r1, r2), solo):
            np.testing.assert_array_equal(five, np.asarray(sf))
            np.testing.assert_array_equal(score, np.asarray(ss))
        after, gauges = _batch_counters()
        assert after.get(tele.C_BATCH_DISPATCHES, 0) \
            - before.get(tele.C_BATCH_DISPATCHES, 0) == 1
        assert after.get(tele.C_BATCH_WINDOWS, 0) \
            - before.get(tele.C_BATCH_WINDOWS, 0) == 2
        assert gauges.get(tele.G_BATCH_JOBS, {}).get("last") == 2
    finally:
        coal.stop()
        tele.TRACE.recording = False
        tele.TRACE.reset()


# ---------------------------------------------------------------------------
# Pipeline-level byte-identity: batched service vs solo runs
# ---------------------------------------------------------------------------
def test_batched_jobs_byte_identical_across_partitioners(
    tmp_path, batch_input, device_backend,
):
    """Three concurrent batched jobs — two on the pool partitioner,
    one pinned to the mesh (whose windows deliberately do NOT coalesce:
    the mesh already fuses the device set) — every output byte-
    identical to the solo baseline, with real fused dispatches and
    full grid-fill accounting."""
    before, _ = _batch_counters()
    sched = JobScheduler(str(tmp_path / "root"), max_jobs=3,
                         batching=True)
    specs = [
        _spec("bp1", batch_input, tmp_path, tenant="A"),
        _spec("bp2", batch_input, tmp_path, tenant="B"),
        _spec("bm3", batch_input, tmp_path, tenant="C",
              partitioner="mesh"),
    ]
    for s in specs:
        assert isinstance(sched.submit(s), Admitted)
    assert sched.wait(timeout=600)
    st = sched.status()["jobs"]
    assert all(v["state"] == DONE for v in st.values()), st
    assert st["bp1"]["tenant"] == "A"
    c, _g = _batch_counters()

    def delta(key):
        return c.get(key, 0) - before.get(key, 0)

    assert delta(tele.C_BATCH_DISPATCHES) > 0, "nothing coalesced"
    assert delta(tele.C_BATCH_WINDOWS) >= delta(tele.C_BATCH_DISPATCHES)
    assert delta(tele.C_BATCH_ROWS_DISPATCHED) >= \
        delta(tele.C_BATCH_ROWS_OCCUPIED) > 0
    sched.close()
    for s in specs:
        assert _parts_hash(s.output) == batch_input["baseline"], s.job_id


def test_fused_dispatch_failure_falls_back_solo_byte_identical(
    tmp_path, batch_input, device_backend,
):
    """The fault matrix's isolation leg: every fused dispatch fails
    (sched.batch=permanent), every window takes the solo-fallback
    detour — counted — and the outputs stay byte-identical."""
    faults.install("sched.batch=permanent")
    try:
        before, _ = _batch_counters()
        sched = JobScheduler(str(tmp_path / "root"), max_jobs=2,
                             batching=True)
        specs = [
            _spec("fb1", batch_input, tmp_path, tenant="A"),
            _spec("fb2", batch_input, tmp_path, tenant="B"),
        ]
        for s in specs:
            assert isinstance(sched.submit(s), Admitted)
        assert sched.wait(timeout=600)
        st = sched.status()["jobs"]
        assert all(v["state"] == DONE for v in st.values()), st
        c, _g = _batch_counters()
        assert c.get(tele.C_BATCH_FALLBACKS, 0) \
            - before.get(tele.C_BATCH_FALLBACKS, 0) > 0, \
            "no fallback was exercised"
        assert c.get(tele.C_BATCH_DISPATCHES, 0) \
            - before.get(tele.C_BATCH_DISPATCHES, 0) == 0
        sched.close()
        for s in specs:
            assert _parts_hash(s.output) == batch_input["baseline"], \
                s.job_id
    finally:
        faults.clear()


def test_job_crash_mid_batch_quarantines_only_that_job(
    tmp_path, batch_input, device_backend,
):
    """A poison job crashing while batched quarantines alone; its
    batch neighbor replays nothing visible — output byte-identical."""
    faults.install("sched.job_crash=permanent,device=bad")
    try:
        sched = JobScheduler(str(tmp_path / "root"), max_jobs=2,
                             batching=True, job_retries=0)
        ok = _spec("ok", batch_input, tmp_path, tenant="A")
        bad = _spec("bad", batch_input, tmp_path, tenant="B")
        assert isinstance(sched.submit(ok), Admitted)
        assert isinstance(sched.submit(bad), Admitted)
        assert sched.wait(timeout=600)
        st = sched.status()["jobs"]
        assert st["ok"]["state"] == DONE
        assert st["bad"]["state"] == QUARANTINED
        sched.close()
        assert _parts_hash(
            str(tmp_path / "ok.adam")
        ) == batch_input["baseline"]
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# Quota enforcement at the scheduler + gateway seam
# ---------------------------------------------------------------------------
def test_quota_429_then_successful_resubmit(tmp_path, monkeypatch):
    """Typed quota refusal with a budget-derived Retry-After, then a
    clean admit once the rolling window frees the spend — without
    touching other tenants (stubbed pipeline: admission-layer test)."""
    from adam_tpu.serve import scheduler as sched_mod

    monkeypatch.setattr(
        sched_mod.streamed_mod, "transform_streamed",
        lambda *a, **kw: {"n_reads": 0, "windows_fresh": 0},
    )
    clock = {"t": 5000.0}
    qm = QuotaManager("greedy:bytes=100", window_s=30.0,
                      clock=lambda: clock["t"])
    sched = JobScheduler(str(tmp_path / "root"), max_jobs=4, quota=qm)
    # burn the greedy tenant's budget (the pacer seam would normally
    # charge this from grant sizes)
    qm.charge("greedy", nbytes=500)
    spec = JobSpec(job_id="g1", input="in.sam", tenant="greedy",
                   output=str(tmp_path / "g1.adam"))
    got = sched.submit(spec)
    assert isinstance(got, Busy) and got.kind == "quota", got
    assert got.retry_after_s is not None and got.retry_after_s >= 1
    c, _ = _batch_counters()
    assert c.get(tele.C_QUOTA_REJECTED, 0) >= 1
    # another tenant admits right through the refusal
    other = JobSpec(job_id="o1", input="in.sam", tenant="polite",
                    output=str(tmp_path / "o1.adam"))
    assert isinstance(sched.submit(other), Admitted)
    # the rolling window frees the spend: the SAME submission admits
    # (the refusal never registered the job id)
    clock["t"] += 31.0
    assert isinstance(sched.submit(spec), Admitted)
    assert sched.wait(timeout=60)
    # status carries the per-tenant quota view
    qst = sched.status()["quota"]
    assert qst is not None and "greedy" in qst["tenants"]
    sched.close()


def test_gateway_maps_quota_busy_to_429(tmp_path, monkeypatch):
    """The wire leg: Busy(kind='quota') -> HTTP 429 with the
    budget-derived Retry-After (NOT the grant-cadence hint)."""
    from adam_tpu.api.transform_service import TransformService
    from adam_tpu.gateway.client import GatewayBusy, GatewayClient
    from adam_tpu.gateway.server import GatewayServer

    svc = TransformService(str(tmp_path / "root"), max_jobs=2)
    monkeypatch.setattr(
        svc.scheduler, "submit",
        lambda spec, recovered=False: Busy(
            "tenant over quota", kind="quota", retry_after_s=77,
        ),
    )
    gw = GatewayServer(svc)
    gw.start()
    try:
        c = GatewayClient(gw.url)
        with pytest.raises(GatewayBusy) as ei:
            c.submit("q1", {"input": "in.sam",
                            "output": str(tmp_path / "q1.adam")})
        assert ei.value.status == 429
        assert ei.value.kind == "quota"
        assert ei.value.retry_after == 77
    finally:
        gw.close()
        svc.close()


# ---------------------------------------------------------------------------
# Heartbeat /4 + dashboards
# ---------------------------------------------------------------------------
def test_heartbeat_batch_fields():
    tr = tele.Tracer(recording=True)
    tr.count(tele.C_BATCH_ROWS_OCCUPIED, 750)
    tr.count(tele.C_BATCH_ROWS_DISPATCHED, 1000)
    tr.gauge(tele.G_BATCH_JOBS, 3)
    hb = tele.Heartbeat([tr], sink="stderr", interval_s=60.0)
    line = hb.sample()
    assert tuple(line.keys()) == tele.HEARTBEAT_FIELDS
    assert line["schema"] == "adam_tpu.heartbeat/7"
    assert line["batch_fill"] == 0.75
    assert line["batched_jobs"] == 3
    # no batching counters -> explicit nulls, never fabricated zeros
    line2 = tele.Heartbeat(
        [tele.Tracer(recording=True)], sink="stderr", interval_s=60.0
    ).sample()
    assert line2["batch_fill"] is None
    assert line2["batched_jobs"] is None


def test_top_renders_fill(capsys):
    from adam_tpu.utils import top as top_mod

    line = {
        "schema": "adam_tpu.heartbeat/4", "seq": 3, "elapsed_s": 4.0,
        "windows_ingested": 4, "windows_total": 8,
        "windows_resumed": 0, "parts_written": 2,
        "reads_ingested": 1000, "reads_per_s": 250.0,
        "bytes_written": 1 << 20, "h2d_bytes": 0, "d2h_bytes": 0,
        "hbm_bytes_in_use": {}, "hbm_peak_bytes": None, "inflight": 0,
        "inflight_per_device": {}, "retries": 0, "faults": 0,
        "devices_evicted": 0, "eta_s": 4.0, "done": False, "ok": True,
        "partitioner": "pool", "batch_fill": 0.62, "batched_jobs": 2,
    }
    text = top_mod.render_frame(line)
    assert "fill 62%" in text and "jobs/dispatch 2" in text
    # /4 lines parse; the fill cell rides the service (pool) stream in
    # the multi-job view
    assert top_mod.parse_heartbeat_text(
        __import__("json").dumps(line) + "\n"
    )
    multi = top_mod.render_multi_frame({"j1": line}, pool=line)
    assert "fill 62%" in multi


def test_analyzer_batching_section():
    from adam_tpu.utils import analyzer

    tr = tele.Tracer(recording=True)
    tr.count(tele.C_BATCH_DISPATCHES, 4)
    tr.count(tele.C_BATCH_WINDOWS, 10)
    tr.count(tele.C_BATCH_ROWS_OCCUPIED, 600)
    tr.count(tele.C_BATCH_ROWS_DISPATCHED, 1000)
    tr.count(tele.C_QUOTA_REJECTED, 1)
    tr.observe(tele.H_BATCH_FILL, 0.6)
    tr.record_quota("tA", nbytes=2048, compute_s=0.5,
                    budget_bytes=4096)
    report = analyzer.analyze(tr.to_json())
    bat = report["batching"]
    assert bat["dispatches"] == 4 and bat["windows"] == 10
    assert bat["dispatches_saved"] == 6
    assert bat["fill"] == 0.6
    assert bat["quota_rejected"] == 1
    assert bat["quota"]["tA"]["bytes"] == 2048
    text = analyzer.render_report(report)
    assert "Batching (cross-job window coalescing)" in text
    assert "6 dispatch(es) saved" in text
    assert "tenant tA" in text
    # solo runs render no batching section at all
    solo = analyzer.analyze(tele.Tracer(recording=True).to_json())
    assert solo["batching"] == {}
    assert "Batching" not in analyzer.render_report(solo)


def test_fused_dispatch_fanin_links_resolve_per_job(device_backend,
                                                    batch_input):
    """The fused dispatch claims NO single trace: its span links every
    contributing job's {job, window, trace}, and each job's trace
    query resolves the SHARED span through its own link — the fan-in
    edge the /trace surface crosses the batch boundary on."""
    from adam_tpu.io import sam as sam_io

    tid1, tid2 = tele.mint_trace_id(), tele.mint_trace_id()
    it = sam_io.iter_sam_batches(batch_input["input"], batch_reads=512)
    b1 = next(it)[0]
    b2 = next(it)[0]
    tele.TRACE.recording = True
    tele.TRACE.reset()  # earlier tests' fused spans must not leak in
    coal = WindowCoalescer(wait_ms=2000.0)
    try:
        c1 = coal.client("j1", trace=tid1)
        c2 = coal.client("j2", trace=tid2)
        f1 = c1.submit_markdup(0, b1)
        f2 = c2.submit_markdup(7, b2)
        f1.result(timeout=120)
        f2.result(timeout=120)
        fused = [e for e in tele.TRACE.events()
                 if e["name"] == tele.SPAN_BATCH_FUSED]
        assert len(fused) == 1
        links = fused[0]["args"]["links"]
        assert sorted(
            (l["job"], l["window"], l["trace"]) for l in links
        ) == [("j1", 0, tid1), ("j2", 7, tid2)]
        # the shared span is IN both traces, via its links...
        for tid in (tid1, tid2):
            assert any(
                e["name"] == tele.SPAN_BATCH_FUSED
                for e in tele.TRACE.events_for_trace(tid)
            ), tid
        # ...and in neither job's export does the OTHER job's link
        # grant membership to a third trace
        assert not tele.TRACE.events_for_trace(tele.mint_trace_id())
        # a deregistered job's trace stops flowing into NEW tickets
        coal.deregister("j1")
        assert coal._job_traces.get("j1") is None
    finally:
        coal.stop()
        tele.TRACE.recording = False
        tele.TRACE.reset()
