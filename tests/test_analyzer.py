"""Run analyzer (utils/analyzer.py): per-device attribution, barrier
decomposition, critical path, report rendering, CLI.

The load-bearing contract: on a trace with device-attributed span
tracks, ``busy_s + idle_s == wall_s`` per device (the smoke-test's
acceptance criterion asserts the same within 5% on a real 2-device
run), nested spans never double-count, replayed work is attributed as
``replay_s``, and the snapshot path reproduces the same totals from
``device_spans`` aggregates alone.
"""

import json

import pytest

from adam_tpu.utils import analyzer
from adam_tpu.utils import telemetry as tele

S = int(1e9)  # ns per second


def _synthetic_two_device_tracer():
    """A 10 s run with a KNOWN layout on two devices.

    device 0: dispatch [1, 3), fetch [5, 6)          -> busy 3, idle 7
    device 1: dispatch [2, 4), dispatch [4, 7)       -> busy 5, idle 5
              (the second dispatch nests a sub-span [4, 5) that must
              NOT double-count into busy)
    host:     pass A [0, 4), resolve [4, 5), merge-fetch [5, 6),
              solve [6, 7), pass C [7, 9), write wait [9, 10)
    """
    tr = tele.Tracer(recording=True)
    t0 = 0

    def add(name, start_s, dur_s, **attrs):
        tr.add_span(name, t0 + start_s * S, dur_s * S, **attrs)

    add(tele.SPAN_TOTAL, 0, 10)
    add(tele.SPAN_PASS_A, 0, 4)
    add(tele.SPAN_RESOLVE, 4, 1)
    add(tele.SPAN_OBS_MERGE, 5, 1)
    add(tele.SPAN_SOLVE, 6, 1)
    add(tele.SPAN_PASS_C, 7, 2)
    add(tele.SPAN_WRITE_WAIT, 9, 1)
    # device 0
    add(tele.SPAN_APPLY_DISPATCH, 1, 2, device=0, window=0)
    add(tele.SPAN_OBS_FETCH, 5, 1, device=0, window=0)
    # device 1 (with a nested sub-interval that must union away)
    add(tele.SPAN_APPLY_DISPATCH, 2, 2, device=1, window=1)
    add(tele.SPAN_APPLY_DISPATCH, 4, 3, device=1, window=3)
    add(tele.SPAN_BQSR_OBSERVE, 4, 1, device=1, window=3)
    return tr


def test_trace_attribution_sums_to_wall():
    tr = _synthetic_two_device_tracer()
    report = analyzer.analyze(tr.to_chrome_trace())
    assert report["kind"] == "trace"
    assert report["wall_s"] == pytest.approx(10.0)
    devs = report["devices"]
    assert set(devs) == {"0", "1"}
    d0, d1 = devs["0"], devs["1"]
    assert d0["busy_s"] == pytest.approx(3.0)
    assert d0["idle_s"] == pytest.approx(7.0)
    assert d0["fetch_s"] == pytest.approx(1.0)
    # nested/overlapping spans union, not sum: busy is 5, not 6
    assert d1["busy_s"] == pytest.approx(5.0)
    assert d1["idle_s"] == pytest.approx(5.0)
    # THE acceptance identity: busy + idle == wall, per device
    for d in devs.values():
        assert d["busy_s"] + d["idle_s"] == pytest.approx(report["wall_s"])
        assert not d["evicted"]


def test_trace_stage_decomposition_and_critical_path():
    tr = _synthetic_two_device_tracer()
    report = analyzer.analyze(tr.to_chrome_trace())
    stages = report["stages"]
    assert stages["pass_a_ingest"]["total_s"] == pytest.approx(4.0)
    assert stages["barrier1_resolve"]["total_s"] == pytest.approx(1.0)
    assert stages["barrier2_observe_fetch"]["total_s"] == pytest.approx(1.0)
    assert stages["write_tail"]["total_s"] == pytest.approx(1.0)
    assert stages["pass_a_ingest"]["frac"] == pytest.approx(0.4)
    cp = report["critical_path"]
    assert cp["edges"], "no critical-path edges"
    # the chain ends at the write tail and is bounded by the run wall
    assert cp["edges"][0]["edge_s"] <= report["wall_s"]
    names = {e["to"] for e in cp["edges"]} | {e["from"] for e in cp["edges"]}
    assert any(tele.SPAN_WRITE_WAIT in n for n in names)
    # window attribution survives into the edge labels
    assert any("[w" in n for n in names)
    # duration histograms are rebuilt from the events
    assert report["histograms"][tele.SPAN_APPLY_DISPATCH]["count"] == 3


def test_trace_replay_and_eviction_attribution():
    tr = tele.Tracer(recording=True)
    tr.add_span(tele.SPAN_TOTAL, 0, 10 * S)
    # device 1 worked [0, 2), then died; its replay umbrella spans [2, 5)
    tr.add_span(tele.SPAN_APPLY_DISPATCH, 0, 2 * S, device=1, window=0)
    tr.add_span(tele.SPAN_POOL_REPLAY, 2 * S, 3 * S, device=1, window=1)
    # the survivor re-ran window 1 inside that umbrella
    tr.add_span(tele.SPAN_APPLY_DISPATCH, 2 * S, 2 * S, device=0, window=1,
                replay=1)
    report = analyzer.analyze(tr.to_chrome_trace())
    d0, d1 = report["devices"]["0"], report["devices"]["1"]
    assert d1["evicted"] is True
    # pre-eviction work stays on the dead chip's row; the umbrella is
    # replay wall, not busy
    assert d1["busy_s"] == pytest.approx(2.0)
    assert d1["replay_s"] == pytest.approx(3.0)
    # the survivor's replayed work counts as ITS busy and replay
    assert d0["evicted"] is False
    assert d0["busy_s"] == pytest.approx(2.0)
    assert d0["replay_s"] == pytest.approx(2.0)


def test_snapshot_mode_matches_device_span_totals():
    tr = _synthetic_two_device_tracer()
    report = analyzer.analyze(tr.snapshot())
    assert report["kind"] == "snapshot"
    assert report["wall_s"] == pytest.approx(10.0)
    devs = report["devices"]
    assert devs["0"]["busy_s"] == pytest.approx(3.0)
    assert devs["0"]["fetch_s"] == pytest.approx(1.0)
    # aggregate mode SUMS (no timestamps): device 1's nested second = 6
    assert devs["1"]["busy_s"] == pytest.approx(6.0)
    # no event ring -> no critical path in snapshot mode
    assert "critical_path" not in report
    # survivor replay keys fold into replay_s
    tr2 = tele.Tracer(recording=True)
    tr2.add_span(tele.SPAN_TOTAL, 0, 4 * S)
    tr2.add_span(tele.SPAN_APPLY_DISPATCH, 0, 1 * S, device=0)
    tr2.add_span(tele.SPAN_APPLY_DISPATCH, 0, 2 * S, device=0, replay=1)
    devs2 = analyzer.analyze(tr2.snapshot())["devices"]
    assert devs2["0"]["busy_s"] == pytest.approx(3.0)
    assert devs2["0"]["replay_s"] == pytest.approx(2.0)


def test_utilization_from_snapshot_is_bench_embeddable():
    tr = _synthetic_two_device_tracer()
    util = analyzer.utilization_from_snapshot(tele.key_stable_snapshot(tr))
    assert util["wall_s"] == pytest.approx(10.0)
    assert set(util["devices"]) == {"0", "1"}
    # the device ledger rides along for the bench artifact (the
    # zero-filled key-stable snapshot yields hits/misses of 0)
    assert util["transfers"] == {}  # no transfers recorded in this run
    assert util["compiles"] == {}
    # the CPU-baseline shape: no device spans -> {} (key-stable)
    empty = analyzer.utilization_from_snapshot(
        tele.key_stable_snapshot(tele.Tracer(recording=True))
    )
    assert empty == {
        "wall_s": None, "devices": {}, "transfers": {}, "compiles": {},
    }


def test_render_report_and_document_kind(tmp_path):
    tr = _synthetic_two_device_tracer()
    text = analyzer.render_report(analyzer.analyze(tr.to_chrome_trace()))
    for needle in ("Per-device attribution", "Stage / barrier",
                   "Critical path", "busy_s"):
        assert needle in text, needle
    with pytest.raises(ValueError):
        analyzer.document_kind({"not": "an artifact"})
    # snapshot docs round-trip through disk (the --metrics-json shape)
    p = tmp_path / "m.json"
    p.write_text(json.dumps(tr.to_json()))
    report = analyzer.analyze_path(str(p))
    assert report["kind"] == "snapshot"


def test_analyze_cli_subcommand(tmp_path, capsys):
    from adam_tpu.cli.main import main

    tr = _synthetic_two_device_tracer()
    trace = tmp_path / "t.json"
    trace.write_text(json.dumps(tr.to_chrome_trace()))
    out_json = tmp_path / "a.json"
    rc = main(["analyze", str(trace), "-json", str(out_json)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Per-device attribution" in out
    doc = json.loads(out_json.read_text())
    assert doc["devices"]["0"]["busy_s"] == pytest.approx(3.0)
    # a non-artifact input is a clean usage error, not a traceback
    bogus = tmp_path / "bogus.json"
    bogus.write_text("{}")
    assert main(["analyze", str(bogus)]) == 2


def test_trace_mode_warns_on_ring_eviction():
    """A truncated flight recorder must not read as idle chips: the
    trace export carries the eviction count and the report surfaces
    it as a warning instead of silently fabricating idle time."""
    tr = tele.Tracer(recording=True, capacity=4)
    for i in range(10):
        tr.add_span(tele.SPAN_APPLY_DISPATCH, i * S, S, device=0, window=i)
    tr.add_span(tele.SPAN_TOTAL, 0, 10 * S)
    doc = tr.to_chrome_trace()
    assert doc["events_evicted"] == 7
    report = analyzer.analyze(doc)
    assert report["events_evicted"] == 7
    assert "WARNING" in analyzer.render_report(report)
    # an un-truncated run reports zero and no warning
    clean = analyzer.analyze(_synthetic_two_device_tracer().to_chrome_trace())
    assert clean["events_evicted"] == 0
    assert "WARNING" not in analyzer.render_report(clean)


def test_mirror_marker_prevents_twin_collapse():
    """Two genuinely-concurrent same-name same-timestamp spans on one
    device must BOTH count (the mirror dedup keys on the explicit cat
    marker, not timestamp coincidence)."""
    tr = tele.Tracer(recording=True)
    tr.add_span(tele.SPAN_TOTAL, 0, 10 * S)
    # identical (name, start, dur, device) twins from two worker threads
    tr.add_span(tele.SPAN_POOL_PREWARM_COMPILE, 0, 2 * S, thread="w0",
                device=0, kernel="k")
    tr.add_span(tele.SPAN_POOL_PREWARM_COMPILE, 0, 2 * S, thread="w1",
                device=0, kernel="k")
    report = analyzer.analyze(tr.to_chrome_trace())
    assert report["devices"]["0"]["n_spans"] == 2
    assert report["histograms"][tele.SPAN_POOL_PREWARM_COMPILE]["count"] == 2


# --------------------------------------------------------------------------
# device ledger sections + resumed-run snapshots
# --------------------------------------------------------------------------
def _resumed_run_snapshot():
    """Synthetic snapshot of a RESUMED 2-device run: this-process work
    only (the skipped windows never dispatched), resume counters set,
    and populated transfer/compile/HBM ledger sections."""
    tr = _synthetic_two_device_tracer()
    tr.count(tele.C_RESUME_WINDOWS_SKIPPED, 3)
    tr.count(tele.C_RESUME_HISTOGRAMS_LOADED, 2)
    tr.count(tele.C_READS_INGESTED, 10_000)
    with tele.pass_scope("observe"):
        tr.record_transfer("d2h", 2_000_000, 0.5, device="0")
        tr.record_transfer("d2h", 2_000_000, 0.25, device="1")
    with tele.pass_scope("apply"):
        tr.record_transfer("h2d", 8_000_000, 0.01, device="0")
    tr.record_compile("bqsr.observe", (1024, 128, 3), "cpu:1", 0.25,
                      in_window=True)
    tr.record_compile("bqsr.apply", (32768, 128, 3, 257), "cpu:0", 0.1,
                      in_window=False)
    tr.count(tele.C_COMPILE_HITS, 7)
    tr.record_hbm("0", 1 << 30, peak_bytes=2 << 30)
    return tr.snapshot()


def test_resumed_run_snapshot_report_renders_ledger_sections():
    """The satellite contract: a resumed run's snapshot analyzes with
    the resume counters surfaced, busy/idle attribution counting only
    this-process spans, and the transfer/compile/HBM sections rendering
    (in-window cold compiles flagged as warnings)."""
    snap = _resumed_run_snapshot()
    report = analyzer.analyze(snap)
    # resume counters present in the report's counter section
    assert report["counters"][tele.C_RESUME_WINDOWS_SKIPPED] == 3
    assert report["counters"][tele.C_RESUME_HISTOGRAMS_LOADED] == 2
    # busy/idle attribution is exactly the this-process span totals
    # (device 0: 2 s dispatch + 1 s fetch; nothing for skipped windows)
    assert report["devices"]["0"]["busy_s"] == pytest.approx(3.0)
    assert report["devices"]["0"]["idle_s"] == pytest.approx(7.0)
    # transfers: totals, per-device split, throughput, bytes-per-read
    xfer = report["transfers"]
    assert xfer["h2d_bytes"] == 8_000_000
    assert xfer["d2h_bytes"] == 4_000_000
    assert xfer["devices"]["0"]["d2h"]["bytes_per_s"] == 4_000_000
    assert xfer["devices"]["0"]["d2h"]["by_pass"] == {"observe": 2_000_000}
    assert xfer["bytes_per_read"] == pytest.approx(1200.0)
    # compile cache: the in-window miss is split out
    comp = report["compiles"]
    assert comp["cache_hits"] == 7 and comp["cache_misses"] == 2
    assert comp["prewarmed"] == 1
    assert [e["kernel"] for e in comp["in_window"]] == ["bqsr.observe"]
    # HBM peaks
    assert report["hbm"]["0"]["peak_bytes"] == 2 << 30
    text = analyzer.render_report(report)
    assert "Tunnel transfers" in text
    assert "WARNING: shapes cold-compiled INSIDE a timed window" in text
    assert "bqsr.observe[1024x128x3]" in text
    assert "HBM footprint" in text
    assert "resume.windows_skipped" in text


def test_hbm_unsupported_marker_when_devices_but_no_samples():
    """A device-attributed run whose backend lacks memory_stats must
    say so explicitly — never render zeros."""
    tr = _synthetic_two_device_tracer()
    report = analyzer.analyze(tr.snapshot())
    assert report["hbm"] == {"unsupported": True}
    assert "unsupported backend" in analyzer.render_report(report)
    # a host-only run (no device attribution) gets no HBM section
    host = tele.Tracer(recording=True)
    host.add_span(tele.SPAN_TOTAL, 0, S)
    host_report = analyzer.analyze(host.snapshot())
    assert host_report["hbm"] == {}


def test_trace_mode_carries_ledger_sections_too():
    """to_chrome_trace embeds transfers/compiles/hbm (+ counters), so
    trace-mode reports render the same ledger sections as snapshots."""
    snap_tr = tele.Tracer(recording=True)
    snap_tr.add_span(tele.SPAN_TOTAL, 0, 10 * S)
    snap_tr.add_span(tele.SPAN_APPLY_DISPATCH, S, S, device=0)
    snap_tr.record_transfer("h2d", 1_000_000, 0.001, device="0",
                            pass_name="apply")
    snap_tr.record_compile("markdup.columns", (4096, 32, 128), "cpu:0",
                           0.3, in_window=True)
    snap_tr.record_hbm("0", 123456)
    doc = snap_tr.to_chrome_trace()
    report = analyzer.analyze(doc)
    assert report["kind"] == "trace"
    assert report["transfers"]["h2d_bytes"] == 1_000_000
    assert len(report["compiles"]["in_window"]) == 1
    assert report["hbm"]["0"]["bytes_in_use"] == 123456
    assert report["counters"][tele.C_H2D_BYTES] == 1_000_000


def test_fused_megakernel_run_composes_incident_slo_perf_sections(
        tmp_path):
    """The satellite contract: a fused-megakernel run's artifact
    sitting next to incident bundles, an SLO budget, and a perf
    ledger analyzes into ONE report where every section composes —
    and per-device busy/idle attribution still sums exactly to the
    wall (new sections must not perturb the accounting)."""
    from adam_tpu.utils import incidents
    from adam_tpu.utils import perfledger
    from adam_tpu.utils import slo

    tr = _synthetic_two_device_tracer()
    # the megakernel tier's marks: fused B->C spans + tier decision
    tr.add_span(tele.SPAN_FUSED_BC, 1 * S, S, device=0, window=0)
    tr.gauge(tele.G_FUSED_BC, 1)
    tr.count(tele.C_FUSED_DISPATCHED, 2)
    snap = tr.snapshot()
    art = tmp_path / "m.json"
    art.write_text(json.dumps(snap))

    # sibling incident bundle
    incidents._reset_for_tests()
    incidents.install(str(tmp_path))
    try:
        incidents.maybe_record("slo.burn", trace_id="ab" * 8,
                               reason="budget burning at 25.0x")
    finally:
        incidents._reset_for_tests()
    # sibling SLO budget (self-contained: targets + cumulative counts)
    eng = slo.SLOEngine(
        slo.parse_slo_spec("t:p99(sched.job.run)<30s"), str(tmp_path))
    eng.observe_job("t", 1.0, ok=True)
    eng.observe_job("t", 99.0, ok=True)  # over the bound
    # sibling perf ledger, newest run regressed
    for i in range(4):
        perfledger.book(str(tmp_path),
                        {"spans.streamed.total.total_s": (10.0, "lower")},
                        run_id=f"r{i}")
    perfledger.book(str(tmp_path),
                    {"spans.streamed.total.total_s": (20.0, "lower")},
                    run_id="slow")

    report = analyzer.analyze_path(str(art))
    # every folded section present
    assert report["incidents"][0]["trigger"] == "slo.burn"
    slo_rep = report["slo"]
    assert slo_rep["objectives"][0]["compliance"] == pytest.approx(0.5)
    trend = report["perf_trend"]
    assert trend["n_runs"] == 5 and trend["runs_flagged"] == 1
    # and the accounting they ride along with is untouched:
    # busy + idle == wall per device, fused span counted as busy
    assert report["wall_s"] == pytest.approx(10.0)
    for dev in ("0", "1"):
        d = report["devices"][dev]
        assert d["busy_s"] + d["idle_s"] == \
            pytest.approx(report["wall_s"])
    assert report["devices"]["0"]["busy_s"] == pytest.approx(4.0)

    text = analyzer.render_report(report)
    for heading in ("Incidents (1 bundle(s))", "SLO", "Perf trend"):
        assert heading in text
    assert "slo.burn" in text
    assert "t:p99(sched.job.run)<30s" in text
