"""Two-process jax.distributed harness (run by test_parallel.py).

Each process contributes one CPU device; the genome mesh spans both, so
the shard_map collectives (all_gather / all_to_all / psum) in
adam_tpu.parallel.dist really cross a process boundary over the gRPC
DCN transport — the single-host simulation of SURVEY §2.6's multi-host
requirement (the reference's analog: Spark executors shuffling over TCP).

Usage: python multihost_harness.py <coordinator> <num_procs> <proc_id>
           [transform <shard_dir> <out_dir>]
Prints "HARNESS OK <checksum>" on success from every process.

The ``transform`` mode runs the COMPOSED flagship transform
(markdup -> realign -> BQSR, the reference's Transform composition)
across the processes over a shared raw shard store: each process owns alternating genome-bin shards,
duplicate-marking summaries and realignment candidates exchange through
spill files (the disk-shuffle role Spark's block manager plays), and
the BQSR observation histograms merge with a REAL cross-process device
``psum`` over the 2-device gRPC mesh.  test_parallel.py asserts the
concatenated output equals the monolithic single-process transform.
"""

import os
import sys

# one CPU device per process, no axon
os.environ["JAX_PLATFORMS"] = "cpu"
flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
flags.append("--xla_force_host_platform_device_count=1")
os.environ["XLA_FLAGS"] = " ".join(flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coordinator, n_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

    from adam_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(coordinator, n_procs, pid)

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from adam_tpu.parallel import dist
    from adam_tpu.parallel.mesh import SHARD_AXIS, genome_mesh

    devices = jax.devices()
    assert len(devices) == n_procs, f"expected {n_procs} devices, got {devices}"
    mesh = genome_mesh(devices)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))

    # ---- distributed sort across processes ----
    m = 64
    rng = np.random.default_rng(1234)
    global_keys = rng.integers(0, 2**40, n_procs * m, dtype=np.int64)
    local = global_keys[pid * m : (pid + 1) * m]
    keys = jax.make_array_from_process_local_data(sharding, local)
    out = dist.distributed_sort_keys(keys, mesh)

    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(out, tiled=True)
    ).ravel()
    real = gathered[gathered != np.iinfo(np.int64).max]
    expected = np.sort(global_keys)
    assert len(real) == len(expected), (len(real), len(expected))
    assert (real == expected).all(), "distributed sort mismatch"

    # ---- psum-combined flagstat-style reduction across processes ----
    import jax.numpy as jnp
    from functools import partial
    from adam_tpu.parallel.mesh import shard_map

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P(),
        check_vma=False,
    )
    def total(x):
        return jax.lax.psum(x.sum(), SHARD_AXIS)

    t = total(keys)
    assert int(t) == int(global_keys.sum()), "psum mismatch"

    print(f"HARNESS OK {int(expected[0]) % 100000}", flush=True)


def transform_main(coordinator: str, n_procs: int, pid: int,
                   shard_dir: str, out_dir: str) -> None:
    """Composed 2-process transform over a shared raw shard store."""
    import pickle

    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

    from adam_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(coordinator, n_procs, pid)

    import glob as globmod
    from functools import partial

    import jax.numpy as jnp
    import numpy as np
    from adam_tpu.parallel.mesh import shard_map
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.parallel import spill
    from adam_tpu.parallel.mesh import SHARD_AXIS, genome_mesh
    from adam_tpu.pipelines import bqsr as bqsr_mod
    from adam_tpu.pipelines import markdup as md_mod
    from adam_tpu.pipelines import realign as realign_mod
    from adam_tpu.pipelines.streamed import _write_part

    # record per-host spans/counters so the merge-barrier telemetry
    # gather below has real per-host data to show skew over
    from adam_tpu.utils import telemetry as _telemetry

    _telemetry.TRACE.recording = True

    mesh = genome_mesh(jax.devices())
    # only real shards: the candidate spills below also live here
    shard_paths = sorted(
        globmod.glob(os.path.join(shard_dir, "shard-*.arrows"))
    )
    mine = [si for si in range(len(shard_paths)) if si % n_procs == pid]

    def load(si):
        b, s, h = spill.read_raw_shard(shard_paths[si])
        return AlignmentDataset(b, s, h)

    def barrier(tag):
        multihost_utils.sync_global_devices(tag)

    # ---- pass A: per-process summaries + indel events ------------------
    summaries = {}
    events_local = []
    header = None
    counts = {}
    for si in mine:
        ds = load(si)
        header = ds.header
        counts[si] = ds.batch.n_rows
        summaries[si] = md_mod.row_summary(ds)
        events_local.extend(
            realign_mod.extract_indel_events(ds.batch.to_numpy())
        )

    # exchange summaries + events (and the header, so a process that
    # owns zero shards can still participate in the collectives)
    # through spill files (disk shuffle)
    with open(os.path.join(shard_dir, f"sum-{pid}.pkl"), "wb") as fh:
        pickle.dump((summaries, events_local, counts, header), fh)
    barrier("summaries")
    all_summaries = {}
    all_events = []
    all_counts = {}
    for p in range(n_procs):
        with open(os.path.join(shard_dir, f"sum-{p}.pkl"), "rb") as fh:
            s, e, c, h = pickle.load(fh)
        all_summaries.update(s)
        all_events.extend(e)
        all_counts.update(c)
        if header is None:
            header = h
    assert header is not None, "no process owned any shard"

    # ---- barrier: global duplicate resolve + target merge (identical
    # decisions in both processes — shard order fixes the splice order)
    order = sorted(all_summaries)
    dup = md_mod.resolve_duplicates(
        md_mod.concat_summaries([all_summaries[si] for si in order])
    )
    dup_slices = {}
    off = 0
    for si in order:
        dup_slices[si] = dup[off: off + all_counts[si]]
        off += all_counts[si]
    targets = realign_mod.merge_events(all_events, header.seq_dict.names)

    def with_dup(ds, si):
        b = ds.batch.to_numpy()
        return ds.with_batch(b.replace(flags=md_mod.apply_duplicate_flags(
            np.asarray(b.flags), dup_slices[si]
        )))

    # ---- pass B: candidate split (pre-BQSR, the reference's markdup ->
    # realign -> BQSR order) + local observation of shard remainders ----
    parts = []
    cand_local = []
    for si in mine:
        ds = with_dup(load(si), si)
        n_valid = ds.batch.n_rows
        if targets:
            b = ds.batch.to_numpy()
            keep = realign_mod.candidate_mask(
                b, targets, header.seq_dict.names
            )
            if keep.any():
                cand_local.append(ds.take_rows(np.flatnonzero(keep)))
                ds = realign_mod.mask_out_candidates(
                    ds, targets, header.seq_dict.names, mask=keep
                )
                n_valid = int(np.asarray(ds.batch.valid).sum())
        if n_valid:
            total, mism, _rg, g = bqsr_mod._observe_device(ds, None)
            parts.append((np.asarray(total), np.asarray(mism), g))

    # exchange candidates; pid 0 realigns them all (boundary-correct)
    # and observes the realigned part so its POST-realignment
    # observations enter the global table
    cpath = os.path.join(shard_dir, f"cand-{pid}.arrows")
    if cand_local:
        cand = AlignmentDataset.concat(cand_local)
        w = spill.RawShardWriter(cpath)
        w.append(cand.batch, cand.sidecar, cand.header)
        w.close()
    barrier("candidates")
    realigned = None
    if pid == 0:
        cands = []
        for p2 in range(n_procs):
            cp = os.path.join(shard_dir, f"cand-{p2}.arrows")
            if os.path.exists(cp):
                b2, s2, h2 = spill.read_raw_shard(cp)
                cands.append(AlignmentDataset(b2, s2, h2))
        if cands:
            realigned = realign_mod.realign_indels(
                AlignmentDataset.concat(cands)
            )
            if realigned.batch.n_rows:
                total, mism, _rg, g = bqsr_mod._observe_device(
                    realigned, None
                )
                parts.append((np.asarray(total), np.asarray(mism), g))

    if parts:
        lt, lm, lgl = bqsr_mod.merge_observations(parts)
    else:
        lt = lm = None
        lgl = 0
    # common table width across processes, then a REAL psum over DCN
    gls = multihost_utils.process_allgather(jnp.int32(lgl))
    gl = int(np.max(np.asarray(gls)))
    n_rg = len(header.read_groups) + 1
    shape = (n_rg, bqsr_mod.N_QUAL, 2 * gl + 1, bqsr_mod.N_DINUC)
    pt = np.zeros(shape, np.int64)
    pm = np.zeros(shape, np.int64)
    if lt is not None:
        o = gl - lgl
        pt[:, :, o: o + 2 * lgl + 1, :] = lt
        pm[:, :, o: o + 2 * lgl + 1, :] = lm

    sharding = NamedSharding(mesh, P(SHARD_AXIS))

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(SHARD_AXIS),),
             out_specs=P(), check_vma=False)
    def psum_hist(x):
        return jax.lax.psum(x, SHARD_AXIS)

    def psum_table(local):
        # exact for counts < 2^53 (f64; i64 vector ops are emulated)
        arr = jax.make_array_from_process_local_data(
            sharding, local.reshape(1, -1).astype(np.float64)
        )
        out = np.asarray(psum_hist(arr))  # replicated: fully addressable
        return out.reshape(shape).astype(np.int64)

    total = psum_table(pt)
    mism = psum_table(pm)
    table = bqsr_mod.solve_recalibration_table(total, mism)

    # ---- telemetry gather at the merge barrier: every process ships
    # its snapshot over the same DCN transport the psum rode, and pid 0
    # writes the per-host skew report next to the output parts ----------
    from adam_tpu.parallel import dist as dist_mod
    from adam_tpu.utils import telemetry

    host_snaps = dist_mod.gather_host_telemetry()
    assert len(host_snaps) == n_procs
    if pid == 0:
        with open(os.path.join(out_dir, "telemetry.json"), "w") as fh:
            import json

            json.dump(telemetry.merge_snapshots(host_snaps), fh, default=str)

    # ---- pass C: apply the global table to shard remainders (re-split
    # under the same rule) and, on pid 0, to the realigned part ----------
    for si in mine:
        ds = with_dup(load(si), si)
        if targets:
            ds = realign_mod.mask_out_candidates(
                ds, targets, header.seq_dict.names
            )
        ds = bqsr_mod.apply_recalibration(ds, table, gl)
        if int(np.asarray(ds.batch.valid).sum()):
            _write_part(out_dir, si, ds, "zstd")
    if realigned is not None:
        realigned = bqsr_mod.apply_recalibration(realigned, table, gl)
        _write_part(out_dir, len(shard_paths), realigned, "zstd")
    barrier("done")
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # ru_maxrss is kilobytes on Linux, bytes on Darwin
    rss_gb = ru / (1e9 if sys.platform == "darwin" else 1e6)
    print(
        f"HARNESS OK {int(total.sum()) % 100000} rss_gb={rss_gb:.2f}",
        flush=True,
    )


def run_composition(
    n_procs: int, shard_dir: str, out_dir: str, timeout: int = 900
) -> list[tuple[str, float]]:
    """Spawn ``n_procs`` OS processes running the composed transform over
    an existing raw shard store -> per-process (output, peak_rss_gb)
    pairs.  Shared by test_parallel.py and the driver's dryrun tail.

    Pipes drain on one thread per child: the children synchronize at
    barriers, so sequential communicate() would deadlock if any
    non-first child filled its pipe before everyone reached "done"."""
    import re
    import socket
    import subprocess
    import threading

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coord = f"localhost:{port}"
    here = os.path.abspath(__file__)
    os.makedirs(out_dir, exist_ok=True)
    procs = [
        subprocess.Popen(
            [sys.executable, here, coord, str(n_procs), str(pid),
             "transform", shard_dir, out_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=dict(os.environ),
        )
        for pid in range(n_procs)
    ]
    outs: list = [None] * n_procs
    errs: list = [None] * n_procs

    def drain(i):
        try:
            outs[i], _ = procs[i].communicate(timeout=timeout)
        except BaseException as e:  # timeout etc: recorded, proc killed
            errs[i] = e
    threads = [
        threading.Thread(target=drain, args=(i,)) for i in range(n_procs)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        for p in procs:
            p.kill()
    results = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if errs[pid] is not None or p.returncode != 0 or not out \
                or "HARNESS OK" not in out:
            raise RuntimeError(
                f"composition proc {pid}/{n_procs} failed "
                f"(rc={p.returncode}, err={errs[pid]!r}):"
                f"\n{(out or '')[-3000:]}"
            )
        m = re.search(r"rss_gb=([0-9.]+)", out)
        if not m:
            raise RuntimeError(
                f"composition proc {pid} reported no RSS:\n{out[-500:]}"
            )
        results.append((out, float(m.group(1))))
    return results


if __name__ == "__main__":
    if len(sys.argv) > 4 and sys.argv[4] == "transform":
        transform_main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                       sys.argv[5], sys.argv[6])
    else:
        main()
