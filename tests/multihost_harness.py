"""Two-process jax.distributed harness (run by test_parallel.py).

Each process contributes one CPU device; the genome mesh spans both, so
the shard_map collectives (all_gather / all_to_all / psum) in
adam_tpu.parallel.dist really cross a process boundary over the gRPC
DCN transport — the single-host simulation of SURVEY §2.6's multi-host
requirement (the reference's analog: Spark executors shuffling over TCP).

Usage: python multihost_harness.py <coordinator> <num_procs> <proc_id>
Prints "HARNESS OK <checksum>" on success from every process.
"""

import os
import sys

# one CPU device per process, no axon
os.environ["JAX_PLATFORMS"] = "cpu"
flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
flags.append("--xla_force_host_platform_device_count=1")
os.environ["XLA_FLAGS"] = " ".join(flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coordinator, n_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

    from adam_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(coordinator, n_procs, pid)

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from adam_tpu.parallel import dist
    from adam_tpu.parallel.mesh import SHARD_AXIS, genome_mesh

    devices = jax.devices()
    assert len(devices) == n_procs, f"expected {n_procs} devices, got {devices}"
    mesh = genome_mesh(devices)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))

    # ---- distributed sort across processes ----
    m = 64
    rng = np.random.default_rng(1234)
    global_keys = rng.integers(0, 2**40, n_procs * m, dtype=np.int64)
    local = global_keys[pid * m : (pid + 1) * m]
    keys = jax.make_array_from_process_local_data(sharding, local)
    out = dist.distributed_sort_keys(keys, mesh)

    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(out, tiled=True)
    ).ravel()
    real = gathered[gathered != np.iinfo(np.int64).max]
    expected = np.sort(global_keys)
    assert len(real) == len(expected), (len(real), len(expected))
    assert (real == expected).all(), "distributed sort mismatch"

    # ---- psum-combined flagstat-style reduction across processes ----
    import jax.numpy as jnp
    from functools import partial
    from jax import shard_map

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P(),
        check_vma=False,
    )
    def total(x):
        return jax.lax.psum(x.sum(), SHARD_AXIS)

    t = total(keys)
    assert int(t) == int(global_keys.sum()), "psum mismatch"

    print(f"HARNESS OK {int(expected[0]) % 100000}", flush=True)


if __name__ == "__main__":
    main()
