"""Spark-embedding executor: multi-partition Arrow round trip.

The driver role (a Spark mapPartitions closure in the north-star
deployment) is played here by the test: it streams partition record
batches into `transform -backend spark - -` over stdin, reads the
result stream, and checks each partition came back transformed exactly
as the in-process pipeline would have produced it.
"""

import io
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.api.spark_executor import StageConfig, apply_stages, serve
from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.io.sam import SamHeader
from adam_tpu.models.dictionaries import (
    RecordGroup,
    RecordGroupDictionary,
    SequenceDictionary,
    SequenceRecord,
)

SD = SequenceDictionary((SequenceRecord("chr1", 100000),))
RGD = RecordGroupDictionary((RecordGroup("rg1", library="lib1"),))


def _partition(seed: int, n: int = 40) -> AlignmentDataset:
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        start = int(rng.integers(100, 5000))
        phred = int(rng.integers(20, 40))
        # a couple of duplicate fragments per partition
        if i % 10 == 1:
            start = 777
        recs.append(dict(
            name=f"p{seed}r{i}", flags=0, contig_idx=0, start=start,
            mapq=60, cigar="20M",
            seq="".join("ACGT"[c] for c in rng.integers(0, 4, 20)),
            qual=chr(33 + phred) * 20, read_group_idx=0, attrs="MD:Z:20",
        ))
    batch, side = pack_reads(recs)
    return AlignmentDataset(batch, side, SamHeader(seq_dict=SD, read_groups=RGD))


def _ipc_stream(parts: list[AlignmentDataset]) -> bytes:
    buf = io.BytesIO()
    writer = None
    for p in parts:
        rb = p.to_arrow().combine_chunks().to_batches()[0]
        if writer is None:
            writer = pa.ipc.new_stream(buf, rb.schema)
        writer.write_batch(rb)
    writer.close()
    return buf.getvalue()


def _check_roundtrip(payload: bytes, parts, cfg):
    out = io.BytesIO(payload)
    reader = pa.ipc.open_stream(out)
    batches = list(reader)
    assert len(batches) == len(parts)
    for src, rb in zip(parts, batches):
        want = apply_stages(src, cfg).compact()
        got = AlignmentDataset.from_arrow(rb)
        wb, gb = want.batch.to_numpy(), got.batch.to_numpy()
        assert gb.n_rows == wb.n_rows
        np.testing.assert_array_equal(
            np.asarray(wb.flags), np.asarray(gb.flags)
        )
        L = min(wb.lmax, gb.lmax)
        np.testing.assert_array_equal(
            np.asarray(wb.quals)[:, :L], np.asarray(gb.quals)[:, :L]
        )
        assert list(want.sidecar.names) == list(got.sidecar.names)


def test_serve_in_process():
    """serve() itself: 3 partitions through markdup+BQSR, one output
    batch per partition, transformed exactly like the local pipeline."""
    parts = [_partition(s) for s in range(3)]
    cfg = StageConfig(mark_duplicates=True, recalibrate=True, realign=False)
    inp = io.BytesIO(_ipc_stream(parts))
    outp = io.BytesIO()
    served = serve(cfg, inp, outp)
    assert served == 3
    _check_roundtrip(outp.getvalue(), parts, cfg)
    # duplicate marking really ran per-partition
    got = AlignmentDataset.from_arrow(
        list(pa.ipc.open_stream(io.BytesIO(outp.getvalue())))[0]
    )
    flags = np.asarray(got.batch.to_numpy().flags)
    assert ((flags & schema.FLAG_DUPLICATE) != 0).sum() > 0


def test_cli_backend_spark_subprocess():
    """The full embedding loop: a driver process pipes partitions into
    `transform -backend spark - -` and reads the results off stdout."""
    parts = [_partition(s) for s in range(4)]
    payload = _ipc_stream(parts)
    proc = subprocess.run(
        [sys.executable, "-m", "adam_tpu.cli.main", "transform", "-", "-",
         "-backend", "spark", "-mark_duplicate_reads",
         "-recalibrate_base_qualities"],
        input=payload, capture_output=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    cfg = StageConfig(mark_duplicates=True, recalibrate=True, realign=False)
    _check_roundtrip(proc.stdout, parts, cfg)
