"""Feature layer tests.

Mirrors the reference's FeatureParserSuite / GeneFeatureRDDSuite
patterns, running against the GTF/BED/narrowPeak fixtures shipped in the
reference test resources.
"""

import numpy as np
import pytest

from adam_tpu.api.datasets import FeatureDataset
from adam_tpu.io import features as fio
from adam_tpu.models.genes import as_genes, reverse_complement

RES = "/root/reference/adam-core/src/test/resources/features"
GTF = f"{RES}/Homo_sapiens.GRCh37.75.trun100.gtf"
BED = f"{RES}/gencode.v7.annotation.trunc10.bed"
PEAK = f"{RES}/wgEncodeOpenChromDnaseGm19238Pk.trunc10.narrowPeak"


class TestGTF:
    @pytest.fixture(scope="class")
    def feats(self):
        return FeatureDataset.load(GTF).batch

    def test_coordinates_converted(self, feats):
        # first record: gene DDX11L1 at 1-based [11869, 14412] closed
        assert feats.start[0] == 11868
        assert feats.end[0] == 14412
        assert feats.contig_names[feats.contig_idx[0]] == "1"

    def test_type_and_ids(self, feats):
        side = feats.sidecar
        assert side.feature_type[0] == "gene"
        assert side.feature_id[0] == "ENSG00000223972"
        assert side.parent_ids[0] == []
        # transcripts parent to the gene
        assert side.feature_type[1] == "transcript"
        assert side.feature_id[1] == "ENST00000456328"
        assert side.parent_ids[1] == ["ENSG00000223972"]
        # exons use exon_id and parent to the transcript
        assert side.feature_type[2] == "exon"
        assert side.feature_id[2] == "ENSE00002234944"
        assert side.parent_ids[2] == ["ENST00000456328"]

    def test_attributes(self, feats):
        assert feats.sidecar.attributes[0]["gene_name"] == "DDX11L1"

    def test_as_genes(self, feats):
        genes = as_genes(feats)
        by_id = {g.id: g for g in genes}
        assert "ENSG00000223972" in by_id
        g = by_id["ENSG00000223972"]
        assert len(g.transcripts) >= 2
        tx = {t.id: t for t in g.transcripts}["ENST00000456328"]
        assert len(tx.exons) == 3
        assert tx.strand is True
        assert tx.region.start == 11868 and tx.region.end == 14409
        # gene regions = union of transcript spans
        assert len(g.regions) == 1
        assert g.regions[0].referenceName == "1"

    def test_filter_by_overlapping_region(self, feats):
        hit = feats.filter_by_overlapping_region("1", 11900, 11950)
        assert len(hit) > 0
        assert (hit.start < 11950).all() and (hit.end > 11900).all()
        assert len(feats.filter_by_overlapping_region("99", 0, 100)) == 0


class TestBED:
    def test_parse(self):
        feats = FeatureDataset.load(BED).batch
        assert len(feats) == 10
        # BED coords pass through unchanged
        first = open(BED).readline().split("\t")
        assert feats.start[0] == int(first[1])
        assert feats.end[0] == int(first[2])
        assert feats.sidecar.feature_type[0] == first[3]

    def test_round_trip(self, tmp_path):
        feats = FeatureDataset.load(BED)
        out = str(tmp_path / "rt.bed")
        feats.save(out)
        back = FeatureDataset.load(out)
        assert np.array_equal(feats.batch.start, back.batch.start)
        assert np.array_equal(feats.batch.end, back.batch.end)
        assert np.array_equal(feats.batch.strand, back.batch.strand)


class TestNarrowPeak:
    def test_parse(self):
        feats = FeatureDataset.load(PEAK).batch
        assert len(feats) == 10
        side = feats.sidecar
        assert "signalValue" in side.attributes[0]
        assert "pValue" in side.attributes[0]


class TestDispatch:
    def test_unknown_extension_rejected(self, tmp_path):
        p = tmp_path / "x.unknown"
        p.write_text("a\t1\t2\n")
        with pytest.raises(ValueError, match="cannot infer"):
            fio.read_features(str(p))

    def test_gzip_and_gff3(self, tmp_path):
        import gzip

        p = tmp_path / "a.gff3.gz"
        with gzip.open(p, "wt") as fh:
            fh.write("1\tens\tgene\t100\t200\t.\t+\t.\tID=g1;Name=G\n")
        feats = fio.read_features(str(p))
        assert len(feats) == 1
        assert feats.start[0] == 99
        assert feats.sidecar.attributes[0]["ID"] == "g1"

    def test_intervals_remap_to_seq_dict(self):
        feats = FeatureDataset.load(BED)
        # target space lists contigs in a different order + an extra one
        own = feats.batch.contig_names
        target = ["decoy"] + list(own)
        iv = feats.intervals(target)
        assert iv.contig.tolist() == (feats.batch.contig_idx + 1).tolist()
        # unknown contigs map to -1
        iv2 = feats.intervals(["nothing"])
        assert (iv2.contig == -1).all()


class TestWigFix:
    def test_expansion(self):
        lines = [
            "fixedStep chrom=chr1 start=100 step=10 span=5",
            "1.0",
            "2.0",
            "fixedStep chrom=chr2 start=1 step=1",
            "0.5",
        ]
        rows = list(fio.wigfix_to_bed_lines(lines))
        assert rows[0] == "chr1\t99\t104\t\t1.0"
        assert rows[1] == "chr1\t109\t114\t\t2.0"
        # span persists across declarations unless reset
        assert rows[2] == "chr2\t0\t5\t\t0.5"


class TestSequenceExtraction:
    def make_tx(self):
        from adam_tpu.models.genes import Exon, CDS, Transcript
        from adam_tpu.models.positions import ReferenceRegion

        exons = (
            Exon("e1", "t", True, ReferenceRegion("1", 2, 6)),
            Exon("e2", "t", True, ReferenceRegion("1", 10, 14)),
        )
        cds = (CDS("t", True, ReferenceRegion("1", 4, 6)),)
        return Transcript("t", ("t",), "g", True, exons, cds)

    def test_forward(self):
        ref = "AACCGGTTAACCGGTT"
        tx = self.make_tx()
        assert tx.extract_transcribed_rna_sequence(ref) == ref[2:14]
        assert tx.extract_spliced_mrna_sequence(ref) == ref[2:6] + ref[10:14]
        assert tx.extract_coding_sequence(ref) == ref[4:6]

    def test_reverse(self):
        from dataclasses import replace

        ref = "AACCGGTTAACCGGTT"
        tx = self.make_tx()
        rtx = replace(
            tx,
            strand=False,
            exons=tuple(
                type(e)(e.id, e.transcript_id, False, e.region)
                for e in tx.exons
            ),
        )
        assert rtx.extract_transcribed_rna_sequence(ref) == reverse_complement(
            ref[2:14]
        )
        # exons emitted 3'->5' in genome order, each revcomped
        assert rtx.extract_spliced_mrna_sequence(ref) == reverse_complement(
            ref[10:14]
        ) + reverse_complement(ref[2:6])

    def test_reverse_complement(self):
        assert reverse_complement("ACGT") == "ACGT"
        assert reverse_complement("AAGG") == "CCTT"
        assert reverse_complement("ANC") == "GNT"


class TestReviewRegressions:
    def test_gff3_hierarchy_wired(self, tmp_path):
        """GFF3 ID=/Parent= and mRNA rows must build a gene model."""
        from adam_tpu.io import features as fio

        p = tmp_path / "x.gff3"
        p.write_text(
            "chr1\tsrc\tgene\t100\t500\t.\t+\t.\tID=gene1\n"
            "chr1\tsrc\tmRNA\t100\t500\t.\t+\t.\tID=tx1;Parent=gene1\n"
            "chr1\tsrc\texon\t100\t200\t.\t+\t.\tID=ex1;Parent=tx1\n"
            "chr1\tsrc\texon\t300\t500\t.\t+\t.\tID=ex2;Parent=tx1\n"
        )
        feats = fio.read_features(str(p))
        assert feats.sidecar.feature_id[:2] == ["gene1", "tx1"]
        assert feats.sidecar.feature_type[1] == "transcript"
        assert feats.sidecar.parent_ids[1] == ["gene1"]
        assert feats.sidecar.parent_ids[2] == ["tx1"]
        from adam_tpu.models.genes import as_genes

        genes = as_genes(feats)
        assert len(genes) == 1
        assert genes[0].id == "gene1"
        assert len(genes[0].transcripts) == 1
        assert len(genes[0].transcripts[0].exons) == 2

    def test_wigfix_scientific_notation_keeps_cursor(self):
        from adam_tpu.io.features import wigfix_to_bed_lines

        rows = list(
            wigfix_to_bed_lines(
                ["fixedStep chrom=chr1 start=10 step=1", "1e-5", "0.5"]
            )
        )
        assert len(rows) == 2
        assert rows[0].split("\t")[:3] == ["chr1", "9", "10"]
        assert rows[0].split("\t")[4] == "1e-5"
        assert rows[1].split("\t")[:3] == ["chr1", "10", "11"]

    def test_wigfix_malformed_line_raises(self):
        import pytest

        from adam_tpu.io.features import wigfix_to_bed_lines

        with pytest.raises(ValueError):
            list(
                wigfix_to_bed_lines(
                    ["fixedStep chrom=chr1 start=10 step=1", "."]
                )
            )

    def test_unknown_contigs_stay_distinct_in_joins(self):
        """Rows on contigs missing from the target dictionary must not
        match each other, and the shuffle join must not crash on them."""
        import numpy as np

        from adam_tpu.formats.features import FeatureBatchBuilder
        from adam_tpu.models.dictionaries import (
            SequenceDictionary,
            SequenceRecord,
        )
        from adam_tpu.pipelines.region_join import (
            broadcast_region_join,
            shuffle_region_join,
        )

        b1 = FeatureBatchBuilder()
        b1.add("chrUn_A", 100, 200)
        b1.add("chr1", 10, 20)
        b2 = FeatureBatchBuilder()
        b2.add("chrUn_B", 150, 250)
        b2.add("chr1", 15, 30)
        sd = SequenceDictionary((SequenceRecord("chr1", 1000),))
        left = b1.build().intervals(["chr1"])
        right = b2.build().intervals(["chr1"])
        li, ri = broadcast_region_join(left, right)
        assert list(zip(li.tolist(), ri.tolist())) == [(1, 1)]
        li, ri = shuffle_region_join(left, right, sd)
        assert list(zip(li.tolist(), ri.tolist())) == [(1, 1)]

    def test_adaptive_trim_tolerates_short_reads(self):
        """A short read in a group whose profile demands a larger trim is
        left untouched instead of aborting the whole dataset."""
        from adam_tpu.api.datasets import AlignmentDataset
        from adam_tpu.formats.batch import pack_reads
        from adam_tpu.io.sam import SamHeader
        from adam_tpu.pipelines import trim

        recs = [
            dict(name="long", flags=0, seq="A" * 20, start=-1, cigar="*",
                 qual="#" * 5 + "I" * 10 + "#" * 5),
            dict(name="short", flags=0, seq="A" * 8, start=-1, cigar="*",
                 qual="#" * 8),
        ]
        batch, side = pack_reads(recs)
        ds = AlignmentDataset(batch, side, SamHeader())
        out = trim.trim_low_quality_read_groups(ds, 10)
        assert out.sidecar.trimmed_from_start[1] == 0
        assert out.sidecar.trimmed_from_end[1] == 0
        assert out.sidecar.trimmed_from_start[0] > 0
