import numpy as np
import pytest

from adam_tpu.formats import schema
from adam_tpu.io import context as ctx
from adam_tpu.io import fasta as fasta_io
from adam_tpu.io import fastq as fastq_io
from adam_tpu.io import sam as sam_io
from adam_tpu.io import parquet as pq_io


def test_read_sam_reads12(ref_resources):
    ds = ctx.load_alignments(str(ref_resources / "reads12.sam"))
    assert len(ds) == 200
    assert ds.seq_dict.names[:2] == ["1", "2"]
    b = ds.batch.to_numpy()
    # first record: simread:1:26472783:false flag 16 pos 26472784 (1-based)
    assert ds.sidecar.names[0] == "simread:1:26472783:false"
    assert int(b.flags[0]) == 16
    assert int(b.start[0]) == 26472783
    assert int(b.end[0]) == 26472783 + 75
    assert int(b.mapq[0]) == 60
    assert schema.decode_bases(b.bases[0], 10) == "GTATAAGAGC"


def test_sam_roundtrip(ref_resources, tmp_path):
    src = str(ref_resources / "small.sam")
    ds = ctx.load_alignments(src)
    out = tmp_path / "out.sam"
    ds.save(str(out))
    ds2 = ctx.load_alignments(str(out))
    assert len(ds2) == len(ds)
    b1, b2 = ds.batch.to_numpy(), ds2.batch.to_numpy()
    np.testing.assert_array_equal(b1.start, b2.start)
    np.testing.assert_array_equal(b1.flags, b2.flags)
    np.testing.assert_array_equal(b1.bases, b2.bases)
    np.testing.assert_array_equal(b1.quals, b2.quals)
    assert ds.sidecar.names == ds2.sidecar.names
    assert ds.sidecar.attrs == ds2.sidecar.attrs


def test_bam_roundtrip(ref_resources, tmp_path):
    ds = ctx.load_alignments(str(ref_resources / "reads12.sam"))
    out = tmp_path / "out.bam"
    ds.save(str(out))
    ds2 = ctx.load_alignments(str(out))
    assert len(ds2) == len(ds)
    b1, b2 = ds.batch.to_numpy(), ds2.batch.to_numpy()
    np.testing.assert_array_equal(b1.start, b2.start)
    np.testing.assert_array_equal(b1.flags, b2.flags)
    np.testing.assert_array_equal(b1.bases, b2.bases)
    np.testing.assert_array_equal(b1.cigar_ops, b2.cigar_ops)
    assert ds.sidecar.names == ds2.sidecar.names
    assert ds.sidecar.attrs == ds2.sidecar.attrs
    assert ds2.seq_dict.names == ds.seq_dict.names


def test_bgzf_blocks(tmp_path):
    data = b"x" * 200_000
    comp = sam_io.bgzf_compress(data)
    assert comp.endswith(sam_io.BGZF_EOF)
    assert sam_io.bgzf_decompress(comp) == data


@pytest.mark.parametrize("n", [1, 2, 3, 4])
def test_interleaved_fastq_fixtures(ref_resources, n):
    path = ref_resources / f"interleaved_fastq_sample{n}.ifq"
    ds = ctx.load_alignments(str(path))
    b = ds.batch.to_numpy()
    assert len(ds) % 2 == 0
    assert (b.flags[b.valid] & schema.FLAG_PAIRED).all()
    firsts = (b.flags[b.valid] & schema.FLAG_FIRST_OF_PAIR) != 0
    assert firsts[0::2].all() and not firsts[1::2].any()
    # names are paired and /1 /2 stripped
    assert ds.sidecar.names[0] == ds.sidecar.names[1]
    assert not ds.sidecar.names[0].endswith("/1")


def _golden_records(path):
    """Extract FASTQ records from the Java InputFormat golden .output files
    (records delimited by >>>...start>>> / <<<...end<<< markers)."""
    body = [
        l
        for l in path.read_text().splitlines()
        if not (l.startswith(">>>") or l.startswith("<<<"))
    ]
    return list(fastq_io.split_fastq_records(body))


@pytest.mark.parametrize("n", [1, 2, 3, 4])
def test_interleaved_record_boundaries_golden(ref_resources, n):
    """Split resync matches the Java InterleavedFastqInputFormat golden output."""
    lines = (
        (ref_resources / f"interleaved_fastq_sample{n}.ifq").read_text().splitlines()
    )
    recs = list(fastq_io.split_fastq_records(lines, resync=True, interleaved=True))
    golden = _golden_records(ref_resources / f"interleaved_fastq_sample{n}.ifq.output")
    assert recs == golden


@pytest.mark.parametrize("n", [1, 2, 3, 4])
def test_single_record_boundaries_golden(ref_resources, n):
    """Split resync matches the Java SingleFastqInputFormat golden output."""
    lines = (ref_resources / f"fastq_sample{n}.fq").read_text().splitlines()
    recs = list(fastq_io.split_fastq_records(lines, resync=True))
    golden = _golden_records(ref_resources / f"single_fastq_sample{n}.fq.output")
    assert recs == golden


def test_multiline_fastq(ref_resources):
    lines = (ref_resources / "multiline_fastq.fq").read_text().splitlines()
    recs = list(fastq_io.split_fastq_records(lines))
    # multiline file has same records as sample1 single-line file
    single = (ref_resources / "interleaved_fastq_sample1.ifq").read_text().splitlines()
    srecs = list(fastq_io.split_fastq_records(single))
    assert [(r[1], r[2]) for r in recs] == [(r[1], r[2]) for r in srecs]


def test_fastq_roundtrip(ref_resources, tmp_path):
    ds = ctx.load_interleaved_fastq(str(ref_resources / "interleaved_fastq_sample1.ifq"))
    out = tmp_path / "out.fq"
    ds.save(str(out))
    reread = out.read_text().splitlines()
    orig = (ref_resources / "interleaved_fastq_sample1.ifq").read_text().splitlines()
    assert reread == orig


def test_paired_fastq_load_and_split(ref_resources, tmp_path):
    ds = ctx.load_paired_fastq(
        str(ref_resources / "proper_pairs_1.fq"),
        str(ref_resources / "proper_pairs_2.fq"),
    )
    b = ds.batch.to_numpy()
    assert (b.flags[b.valid] & schema.FLAG_PAIRED).all()
    p1, p2 = tmp_path / "r1.fq", tmp_path / "r2.fq"
    ds.save_paired_fastq(str(p1), str(p2))
    assert p1.read_text().splitlines() == (
        (ref_resources / "proper_pairs_1.fq").read_text().splitlines()
    )


def test_fasta_fragments_and_region(ref_resources):
    frags, sd, descs = fasta_io.read_fasta(
        str(ref_resources / "artificial.fa"), fragment_length=100
    )
    assert sd.names == ["artificial"]
    total = sd["artificial"].length
    assert frags.n_rows == -(-total // 100)
    region = frags.extract_region(0, 50, 170)
    assert len(region) == 120
    # cross-check against unfragmented read
    frags1, _, _ = fasta_io.read_fasta(str(ref_resources / "artificial.fa"))
    assert frags1.extract_region(0, 50, 170) == region


def test_fasta_roundtrip(ref_resources, tmp_path):
    frags, sd, _ = fasta_io.read_fasta(str(ref_resources / "artificial.fa"))
    out = tmp_path / "out.fa"
    fasta_io.write_fasta(str(out), frags, sd)
    frags2, sd2, _ = fasta_io.read_fasta(str(out))
    assert sd2.names == sd.names
    assert frags2.extract_region(0, 0, sd["artificial"].length) == frags.extract_region(
        0, 0, sd["artificial"].length
    )


def test_parquet_roundtrip(ref_resources, tmp_path):
    ds = ctx.load_alignments(str(ref_resources / "small.sam"))
    out = tmp_path / "small.adam"
    ds.save(str(out))
    ds2 = ctx.load_alignments(str(out))
    assert len(ds2) == len(ds)
    b1, b2 = ds.batch.to_numpy(), ds2.batch.to_numpy()
    np.testing.assert_array_equal(b1.start, b2.start)
    np.testing.assert_array_equal(b1.bases, b2.bases)
    assert ds2.seq_dict.names == ds.seq_dict.names
    assert ds2.sidecar.names == ds.sidecar.names


def test_parquet_projection_predicate(ref_resources, tmp_path):
    import pyarrow.compute as pc

    ds = ctx.load_alignments(str(ref_resources / "reads12.sam"))
    out = tmp_path / "reads12.adam"
    ds.save(str(out))
    proj = ctx.load_parquet_alignments(str(out), projection=["sequence", "flags"])
    assert len(proj) == len(ds)
    assert all(n == "" for n in proj.sidecar.names)  # readName pruned
    filt = ctx.load_parquet_alignments(
        str(out), predicate=pc.field("start") < 100_000_000
    )
    assert 0 < len(filt) < len(ds)
    assert (np.asarray(filt.batch.start)[np.asarray(filt.batch.valid)] < 1e8).all()


def test_missing_qual_roundtrip(tmp_path):
    """qual '*' must stay '*' through SAM and BAM, not become phred-0."""
    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io.sam import SamHeader
    from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord

    sd = SequenceDictionary((SequenceRecord("1", 1000),))
    recs = [dict(name="nq", flags=0, contig_idx=0, start=5, mapq=60,
                 cigar="4M", seq="ACGT", qual="*")]
    batch, side = pack_reads(recs)
    assert not bool(batch.to_numpy().has_qual[0])
    ds = AlignmentDataset(batch, side, SamHeader(seq_dict=sd))
    for ext in ("sam", "bam"):
        p = tmp_path / f"nq.{ext}"
        ds.save(str(p))
        back = ctx.load_alignments(str(p))
        assert not bool(back.batch.to_numpy().has_qual[0]), ext
    line = [l for l in (tmp_path / "nq.sam").read_text().splitlines()
            if not l.startswith("@")][0]
    assert line.split("\t")[10] == "*"


def test_crlf_sam_header(tmp_path):
    """CRLF line endings must not leak \\r into header names."""
    p = tmp_path / "crlf.sam"
    p.write_bytes(
        b"@HD\tVN:1.6\r\n@SQ\tSN:chr1\tLN:1000\r\n"
        b"r1\t0\tchr1\t10\t60\t4M\t*\t0\t0\tACGT\tIIII\r\n"
    )
    ds = ctx.load_alignments(str(p))
    assert ds.header.seq_dict.names == ["chr1"]
    assert np.asarray(ds.batch.contig_idx)[0] == 0


def test_unknown_rg_tag_roundtrips(tmp_path):
    """An RG tag naming a group absent from the header survives save."""
    p = tmp_path / "ghostrg.sam"
    p.write_bytes(
        b"@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:1000\n"
        b"r1\t0\tchr1\t10\t60\t4M\t*\t0\t0\tACGT\tIIII\tRG:Z:ghost\tNM:i:0\n"
    )
    ds = ctx.load_alignments(str(p))
    assert np.asarray(ds.batch.read_group_idx)[0] == -1
    assert "RG:Z:ghost" in ds.sidecar.attrs.to_list()[0]
    out = tmp_path / "ghostrg_out.sam"
    ds.save(str(out))
    body = [l for l in out.read_text().splitlines() if not l.startswith("@")]
    assert "RG:Z:ghost" in body[0]


def test_malformed_bam_no_crash(tmp_path):
    """A corrupt BAM record must raise/fall back, never crash the process."""
    import struct

    from adam_tpu import native

    rec = bytearray(32)
    struct.pack_into("<i", rec, 0, 28)
    rec[12] = 0  # l_read_name = 0 -> invalid
    assert native.tokenize_bam(bytes(rec), 0, []) is None


def test_corrupt_bgzf_rejected():
    """Bit-rot in a BGZF payload or a bad BSIZE must not be accepted."""
    from adam_tpu import native
    from adam_tpu.io.sam import bgzf_compress

    if native.bgzf_compress(b"") is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    data = b"payload " * 5000
    enc = bytearray(bgzf_compress(data))
    enc[30] ^= 0x01  # flip a bit inside the first deflate payload
    assert native.bgzf_decompress(bytes(enc)) is None  # CRC catches it
    small = bytearray(bgzf_compress(b"abc"))
    small[16], small[17] = 19, 0  # BSIZE-1 = 19 -> total 20 < header+trailer
    assert native.bgzf_decompress(bytes(small)) is None


def test_corrupt_bam_array_tag_no_crash():
    """A B-array tag with a bogus element count must not read OOB."""
    import struct

    from adam_tpu import native

    body = bytearray()
    body += struct.pack("<iiBBHHHiiii", -1, -1, 2, 0, 0, 0, 4, 0, -1, -1, 0)
    body += b"r\x00"
    body += b"XXBi" + struct.pack("<I", 0x0FFFFFFF)  # count with no elements
    rec = struct.pack("<i", len(body)) + bytes(body)
    assert native.tokenize_bam(rec, 0, []) is None


def test_duplicate_md_tag_last_wins(tmp_path):
    """Duplicate MD tags: the last one wins on every parse path."""
    p = tmp_path / "dupmd.sam"
    p.write_bytes(
        b"@HD\tVN:1.6\n@SQ\tSN:chr1\tLN:1000\n"
        b"r1\t0\tchr1\t10\t60\t4M\t*\t0\t0\tACGT\tIIII\tMD:Z:4\tMD:Z:2A1\n"
    )
    ds = ctx.load_alignments(str(p))
    assert ds.sidecar.md[0] == "2A1"


def test_paired_fastq_stringency(tmp_path):
    """ValidationStringency on paired export (adamSaveAsPairedFastq,
    AlignmentRecordRDDFunctions.scala:386-464): STRICT raises on
    unpaired names, LENIENT writes only the proper pairs."""
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io import fastq

    base = schema.FLAG_PAIRED
    records = [
        dict(name="p1", flags=base | schema.FLAG_FIRST_OF_PAIR, seq="ACGT",
             qual="IIII", cigar="*", contig_idx=-1, start=-1, mapq=255),
        dict(name="p1", flags=base | schema.FLAG_SECOND_OF_PAIR, seq="TTTT",
             qual="IIII", cigar="*", contig_idx=-1, start=-1, mapq=255),
        dict(name="orphan", flags=base | schema.FLAG_FIRST_OF_PAIR, seq="GGGG",
             qual="IIII", cigar="*", contig_idx=-1, start=-1, mapq=255),
    ]
    batch, side = pack_reads(records)
    p1, p2 = tmp_path / "r1.fq", tmp_path / "r2.fq"

    with pytest.raises(ValueError, match="exactly twice"):
        fastq.write_paired_fastq(str(p1), str(p2), batch, side,
                                 stringency="strict")

    fastq.write_paired_fastq(str(p1), str(p2), batch, side,
                             stringency="lenient")
    assert p1.read_text().count("@") == 1  # orphan dropped
    assert "GGGG" not in p1.read_text()
    assert p2.read_text().count("@") == 1


def test_interleaved_fastq_stringency(tmp_path):
    bad = tmp_path / "bad.ifq"
    bad.write_text(
        "@a/1\nACGT\n+\nIIII\n@b/2\nTTTT\n+\nIIII\n"
    )
    from adam_tpu.io import fastq

    with pytest.raises(ValueError, match="pair mismatch"):
        fastq.read_interleaved_fastq(str(bad), stringency="strict")
    batch, side, _ = fastq.read_interleaved_fastq(str(bad), stringency="lenient")
    assert int(np.asarray(batch.valid).sum()) == 2


def test_arrow_roundtrip(ref_resources):
    """AlignmentDataset <-> pyarrow RecordBatch round-trip (the Spark
    embedding seam, BASELINE north star)."""
    import pyarrow as pa

    from adam_tpu.api.datasets import AlignmentDataset

    ds = ctx.load_alignments(str(ref_resources / "small.sam"))
    table = ds.to_arrow()
    assert isinstance(table, pa.Table)
    batches = table.to_batches()
    ds2 = AlignmentDataset.from_arrow(batches)
    b1, b2 = ds.batch.to_numpy(), ds2.batch.to_numpy()
    assert len(ds2) == len(ds)
    np.testing.assert_array_equal(b1.bases, b2.bases)
    np.testing.assert_array_equal(b1.quals, b2.quals)
    np.testing.assert_array_equal(b1.start, b2.start)
    np.testing.assert_array_equal(b1.flags, b2.flags)
    np.testing.assert_array_equal(b1.cigar_ops, b2.cigar_ops)
    np.testing.assert_array_equal(b1.cigar_lens, b2.cigar_lens)
    assert ds2.seq_dict.names == ds.seq_dict.names
    assert ds2.sidecar.names == ds.sidecar.names
    assert ds2.sidecar.md == ds.sidecar.md


def test_streaming_bam_matches_whole_file(tmp_path):
    """iter_bam_batches (windowed BGZF + record-carry) must reproduce
    read_bam exactly, across window and batch boundaries."""
    from adam_tpu import native
    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.io import sam

    if not native.available():
        pytest.skip("native codec unavailable")

    import sys
    sys.path.insert(0, "/root/repo/tools")
    from make_synth_sam import make_sam

    sam_path = tmp_path / "stream.sam"
    make_sam(str(sam_path), 4000, 100)
    ds = AlignmentDataset.load(str(sam_path))
    bam_path = tmp_path / "stream.bam"
    ds.save(str(bam_path))

    whole, wside, whdr = sam.read_bam(str(bam_path))
    parts = list(
        sam.iter_bam_batches(str(bam_path), batch_reads=1000,
                             window_bytes=64 * 1024)
    )
    assert len(parts) >= 3
    got = np.concatenate([np.asarray(b.start)[np.asarray(b.valid)]
                          for b, _, _ in parts])
    exp = np.asarray(whole.start)[np.asarray(whole.valid)]
    np.testing.assert_array_equal(got, exp)
    got_names = [n for _, s, _ in parts for n in s.names]
    assert got_names == list(wside.names)
    total = sum(int(np.asarray(b.valid).sum()) for b, _, _ in parts)
    assert total == 4000


def test_native_sam_tokenizer_fuzz(tmp_path):
    """Differential fuzz: the C++ SAM tokenizer must agree with the
    pure-Python parser on randomized records (odd names, missing quals,
    clips, indels, tags, CR-LF, unmapped reads)."""
    from adam_tpu import native
    from adam_tpu.io import sam as sam_io

    if not native.available():
        pytest.skip("native codec unavailable")

    rng = np.random.default_rng(99)
    bases = "ACGTN"
    lines = [
        "@HD\tVN:1.5",
        "@SQ\tSN:c1\tLN:100000",
        "@SQ\tSN:c2\tLN:50000",
        "@RG\tID:rgA\tSM:s",
    ]
    for i in range(300):
        L = int(rng.integers(1, 40))
        seq = "".join(bases[j] for j in rng.integers(0, 5, L))
        qual = ("".join(chr(33 + int(q)) for q in rng.integers(0, 60, L))
                if rng.random() > 0.2 else "*")
        mapped = rng.random() > 0.25
        if mapped:
            contig = "c1" if rng.random() > 0.5 else "c2"
            pos = int(rng.integers(1, 1000))
            s = int(rng.integers(0, L))
            cig = f"{s}S{L - s}M" if s and rng.random() > 0.5 else f"{L}M"
            flag = 0 if rng.random() > 0.5 else 16
        else:
            contig, pos, cig, flag = "*", 0, "*", 4
        tags = []
        if rng.random() > 0.5:
            tags.append(f"NM:i:{int(rng.integers(0, 5))}")
        if rng.random() > 0.7:
            tags.append(f"MD:Z:{L}")
        if rng.random() > 0.5:
            tags.append("RG:Z:rgA")
        name = f"r{i}" + ("/1" if rng.random() > 0.8 else "")
        fields = [name, str(flag), contig, str(pos), "60", cig, "*", "0",
                  "0", seq, qual] + tags
        lines.append("\t".join(fields))

    text = "\n".join(lines) + "\n"
    p1 = tmp_path / "fuzz.sam"
    p1.write_text(text)
    # CRLF variant must parse identically
    p2 = tmp_path / "fuzz_crlf.sam"
    p2.write_bytes(text.replace("\n", "\r\n").encode())

    import jax

    nat_b, nat_s, _ = sam_io.read_sam(str(p1))
    # force the pure-python path
    orig = native.tokenize_sam
    native.tokenize_sam = lambda *a, **k: None
    try:
        py_b, py_s, _ = sam_io.read_sam(str(p1))
    finally:
        native.tokenize_sam = orig
    for f in ("bases", "quals", "lengths", "flags", "contig_idx", "start",
              "end", "mapq", "cigar_ops", "cigar_lens", "cigar_n",
              "read_group_idx", "has_qual", "valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(nat_b, f)), np.asarray(getattr(py_b, f)),
            err_msg=f,
        )
    assert list(nat_s.names) == list(py_s.names)
    assert list(nat_s.md) == list(py_s.md)
    assert list(nat_s.attrs) == list(py_s.attrs)

    crlf_b, crlf_s, _ = sam_io.read_sam(str(p2))
    np.testing.assert_array_equal(
        np.asarray(crlf_b.bases), np.asarray(nat_b.bases)
    )
    assert list(crlf_s.names) == list(nat_s.names)


def test_native_bam_roundtrip_fuzz(tmp_path):
    """Randomized SAM -> BAM -> parse roundtrip through the native BGZF +
    BAM tokenizer preserves every column."""
    from adam_tpu.api.datasets import AlignmentDataset

    import sys
    sys.path.insert(0, "/root/repo/tools")
    from make_synth_sam import make_sam

    p = tmp_path / "r.sam"
    make_sam(str(p), 2000, 73)
    ds = AlignmentDataset.load(str(p))
    bam = tmp_path / "r.bam"
    ds.save(str(bam))
    ds2 = AlignmentDataset.load(str(bam))
    b1, b2 = ds.batch.to_numpy(), ds2.batch.to_numpy()
    for f in ("bases", "quals", "lengths", "flags", "contig_idx", "start",
              "cigar_ops", "cigar_lens", "cigar_n"):
        np.testing.assert_array_equal(
            np.asarray(getattr(b1, f)), np.asarray(getattr(b2, f)), err_msg=f
        )
    assert list(ds.sidecar.names) == list(ds2.sidecar.names)
    assert list(ds.sidecar.md) == list(ds2.sidecar.md)


def test_native_bam_encoder_bytewise(ref_resources, tmp_path):
    """The C++ BAM encoder must produce the pure-Python writer's exact
    bytes (records, tags incl. MD/OQ/RG, nibble packing)."""
    from adam_tpu import native
    from adam_tpu.io import sam as sam_io

    if not native.available():
        pytest.skip("native codec unavailable")
    ds = ctx.load_alignments(str(ref_resources / "small.sam"))
    p_nat = tmp_path / "nat.bam"
    p_py = tmp_path / "py.bam"
    sam_io.write_bam(str(p_nat), ds.batch, ds.sidecar, ds.header)
    orig = native.bam_encode
    native.bam_encode = lambda *a, **k: None
    try:
        sam_io.write_bam(str(p_py), ds.batch, ds.sidecar, ds.header)
    finally:
        native.bam_encode = orig
    assert (
        sam_io.bgzf_decompress(p_nat.read_bytes())
        == sam_io.bgzf_decompress(p_py.read_bytes())
    )


def test_native_sam_writer_bytewise(ref_resources, tmp_path):
    """The C++ SAM formatter must produce the pure-Python writer's exact
    text (positions, '=', tags, missing quals)."""
    from adam_tpu import native
    from adam_tpu.io import sam as sam_io

    if not native.available():
        pytest.skip("native codec unavailable")
    ds = ctx.load_alignments(str(ref_resources / "small.sam"))
    p_nat, p_py = tmp_path / "n.sam", tmp_path / "p.sam"
    sam_io.write_sam(str(p_nat), ds.batch, ds.sidecar, ds.header)
    orig = native.sam_encode
    native.sam_encode = lambda *a, **k: None
    try:
        sam_io.write_sam(str(p_py), ds.batch, ds.sidecar, ds.header)
    finally:
        native.sam_encode = orig
    assert p_nat.read_bytes() == p_py.read_bytes()


def test_native_fastq_writer_bytewise(ref_resources, tmp_path):
    """The C++ FASTQ formatter matches the python writer byte for byte
    (revcomp of reverse-strand reads, /1 /2 suffixes)."""
    from adam_tpu import native
    from adam_tpu.io import fastq as fq

    if not native.available():
        pytest.skip("native codec unavailable")
    ds = ctx.load_alignments(str(ref_resources / "small.sam"))
    p_nat, p_py = tmp_path / "n.fq", tmp_path / "p.fq"
    fq.write_fastq(str(p_nat), ds.batch, ds.sidecar)
    orig = native.fastq_encode
    native.fastq_encode = lambda *a, **k: None
    try:
        fq.write_fastq(str(p_py), ds.batch, ds.sidecar)
    finally:
        native.fastq_encode = orig
    assert p_nat.read_bytes() == p_py.read_bytes()


def test_multi_file_load_merges_dictionaries(tmp_path):
    """Directory/glob loads union every file's sequence + read-group
    dictionaries and re-index the batches (loadBam's header merge,
    rdd/ADAMContext.scala:236-257, SequenceDictionary.scala:96-119)."""
    import numpy as np

    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io import context
    from adam_tpu.io.sam import SamHeader, write_sam
    from adam_tpu.models.dictionaries import (
        RecordGroup, RecordGroupDictionary, SequenceDictionary,
        SequenceRecord,
    )

    def mk(path, contigs, rg, names):
        sd = SequenceDictionary(
            tuple(SequenceRecord(n, 10_000) for n in contigs)
        )
        rgd = RecordGroupDictionary((RecordGroup(rg, library="lib_" + rg),))
        recs = [
            dict(name=nm, flags=0, contig_idx=len(contigs) - 1, start=100 + i,
                 mapq=60, cigar="4M", seq="ACGT", qual="IIII",
                 read_group_idx=0)
            for i, nm in enumerate(names)
        ]
        batch, side = pack_reads(recs)
        write_sam(path, batch, side, SamHeader(seq_dict=sd, read_groups=rgd))

    d = tmp_path / "multi"
    d.mkdir()
    # disjoint read groups; partially overlapping contigs
    mk(str(d / "a.sam"), ["chr1", "chr2"], "rgA", ["a1", "a2"])
    mk(str(d / "b.sam"), ["chr2", "chr3"], "rgB", ["b1"])

    for src in [str(d), str(d / "*.sam")]:
        ds = context.load_alignments(src)
        assert ds.seq_dict.names == ["chr1", "chr2", "chr3"]
        assert sorted(ds.read_groups.names) == ["rgA", "rgB"]
        b = ds.batch.to_numpy()
        by_name = {ds.sidecar.names[i]: i for i in range(b.n_rows)}
        # a-reads sat on their file's last contig (chr2), b's on chr3
        assert ds.seq_dict.names[b.contig_idx[by_name["a1"]]] == "chr2"
        assert ds.seq_dict.names[b.contig_idx[by_name["b1"]]] == "chr3"
        rg_names = ds.read_groups.names
        assert rg_names[b.read_group_idx[by_name["a2"]]] == "rgA"
        assert rg_names[b.read_group_idx[by_name["b1"]]] == "rgB"


def test_multi_file_load_conflicting_contigs(tmp_path):
    """Same contig name with different lengths must fail the merge."""
    import pytest as _pytest

    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io import context
    from adam_tpu.io.sam import SamHeader, write_sam
    from adam_tpu.models.dictionaries import (
        SequenceDictionary, SequenceRecord,
    )

    d = tmp_path / "bad"
    d.mkdir()
    for i, ln in enumerate([10_000, 20_000]):
        sd = SequenceDictionary((SequenceRecord("chr1", ln),))
        batch, side = pack_reads([
            dict(name=f"r{i}", flags=0, contig_idx=0, start=10, mapq=60,
                 cigar="4M", seq="ACGT", qual="IIII")
        ])
        write_sam(str(d / f"{i}.sam"), batch, side, SamHeader(seq_dict=sd))
    with _pytest.raises(ValueError):
        context.load_alignments(str(d))


def test_genotype_projection_and_predicate_pushdown(ref_resources, tmp_path):
    """Field-enum projection + variant predicate pushdown on the
    genotype Parquet store (projections/GenotypeField.scala analog):
    unprojected columns come back as defaults, filtered genotype rows
    re-index into the filtered variant batch."""
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from adam_tpu.api.datasets import GenotypeDataset
    from adam_tpu.io import parquet as pio

    gt = GenotypeDataset.load(str(ref_resources / "small.vcf"))
    out = str(tmp_path / "g.adam")
    gt.save(out)

    full_v, full_g, _ = pio.load_genotypes(out)
    cut = int(np.median(full_v.start))
    v, g, _ = pio.load_genotypes(
        out,
        projection=["contig", "start", "genotypeQuality", "qual"],
        filters=pc.field("start") >= cut,
    )
    keep = np.flatnonzero(full_v.start >= cut)
    np.testing.assert_array_equal(v.start, full_v.start[keep])
    # projected-in columns survive; projected-out come back as defaults
    kept_g = np.flatnonzero(np.isin(full_g.variant_idx, keep))
    np.testing.assert_array_equal(
        g.gq, full_g.gq[kept_g]
    )
    assert (g.dp == -1).all()  # readDepth was projected away
    # re-indexed variant_idx points at the FILTERED variant batch
    np.testing.assert_array_equal(
        v.start[g.variant_idx],
        full_v.start[full_g.variant_idx[kept_g]],
    )
    # column pruning is real at the scan layer: the projected read
    # materializes a fraction of the full table's bytes
    import os

    vp = os.path.join(out, "variants.parquet")
    nb_full = pq.read_table(vp).nbytes
    nb_proj = pq.read_table(vp, columns=["start"]).nbytes
    assert nb_proj < nb_full

    with pytest.raises(ValueError, match="projection field"):
        pio.load_genotypes(out, projection=["bogusField"])


def test_feature_fragment_projection_pushdown(tmp_path, ref_resources):
    """Feature/fragment loads honor projection and predicate; pruned
    columns come back as defaults (FeatureField.scala /
    NucleotideContigFragmentField.scala analogs)."""
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from adam_tpu.cli.main import main
    from adam_tpu.io import parquet as pio

    bed = tmp_path / "x.bed"
    # attributes carry a deliberately fat payload so pruning is visible
    rows = [
        f"chr1\t{10 * i}\t{10 * i + 5}\tpeak{i}\t{i}.5\t+"
        for i in range(50)
    ]
    bed.write_text("\n".join(rows) + "\n")
    adam = str(tmp_path / "f.adam")
    assert main(["features2adam", str(bed), adam]) == 0

    full = pio.load_features(adam)
    f = pio.load_features(
        adam, projection=["score"], filters=pc.field("start") >= 100
    )
    keep = np.flatnonzero(full.start >= 100)
    np.testing.assert_array_equal(f.start, full.start[keep])
    np.testing.assert_array_equal(f.score, full.score[keep])
    assert all(x is None for x in f.sidecar.feature_id)  # pruned
    nb_full = pq.read_table(adam).nbytes
    nb_proj = pq.read_table(adam, columns=["start", "end"]).nbytes
    assert nb_proj < nb_full
    with pytest.raises(ValueError, match="feature projection"):
        pio.load_features(adam, projection=["sequence"])

    # fragments
    fa = ref_resources / "contigs.fa"
    if not fa.exists():
        fa = ref_resources / "artificial.fa"
    frag_adam = str(tmp_path / "c.adam")
    assert main(["fasta2adam", str(fa), frag_adam]) == 0
    full_fr, _, descs = pio.load_fragments(frag_adam)
    fr, _, descs2 = pio.load_fragments(frag_adam, projection=["contig"])
    np.testing.assert_array_equal(fr.lengths, full_fr.lengths)
    assert descs2 == {}  # description projected away
    with pytest.raises(ValueError, match="fragment projection"):
        pio.load_fragments(frag_adam, projection=["nope"])


def test_typed_variant_annotations_round_trip(tmp_path):
    """anno2adam stores the reference's named INFO keys as typed Parquet
    columns (VariantAnnotationConverter.scala:52-155 analog), predicates
    push down on them, and adam2vcf restores the original INFO keys."""
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from adam_tpu.cli.main import main
    from adam_tpu.io import parquet as pio

    vcf = tmp_path / "anno.vcf"
    vcf.write_text("\n".join([
        "##fileformat=VCFv4.1",
        "##contig=<ID=chr1,length=1000>",
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO",
        "chr1\t11\trs1\tA\tG\t50\tPASS\t"
        "PHYLOP=2.31;SIFT_PRED=D;SIFT_SCORE=0.02;AA=G;GENEINFO=BRCA1:672;"
        "MQ=58.7;DP=42;QD=11.5;VQSLOD=1234.5678;culprit=MQ;"
        "NEGATIVE_TRAIN_SITE;"
        "MYSTERY=7",
        "chr1\t21\trs2\tC\tT\t60\tPASS\tPHYLOP=-0.5;DP=10",
    ]) + "\n")
    import os

    adam = str(tmp_path / "anno.adam")
    assert main(["anno2adam", str(vcf), adam]) == 0

    vt = pq.read_table(os.path.join(adam, "variants.parquet"))
    import pyarrow as pa

    # typed columns with typed storage (float64 so VQSLOD-style values
    # round-trip value-exact through the column back to VCF text)
    assert vt.schema.field("ann_phylop").type == pa.float64()
    assert vt.schema.field("ann_readDepth").type == pa.int64()
    assert vt.schema.field("ann_usedForNegativeTrainingSet").type == pa.bool_()
    assert vt.schema.field("ann_culprit").type == pa.string()
    # unknown keys stay in the generic string map
    import json as _json

    annos = [_json.loads(s) for s in vt["annotations"].to_pylist()]
    assert annos[0] == {"MYSTERY": "7"}

    # predicate pushdown on a typed annotation column
    v, _g, _sd = pio.load_genotypes(
        adam, filters=pc.field("ann_phylop") > 0
    )
    assert len(v.start) == 1 and int(v.start[0]) == 10

    # round trip back to VCF restores the original INFO keys
    out_vcf = str(tmp_path / "out.vcf")
    assert main(["adam2vcf", adam, out_vcf]) == 0
    body = [
        ln for ln in open(out_vcf).read().splitlines()
        if not ln.startswith("#")
    ]
    row1 = dict(
        item.split("=", 1) if "=" in item else (item, True)
        for item in body[0].split("\t")[7].split(";")
    )
    assert row1["PHYLOP"] == "2.31" and row1["SIFT_PRED"] == "D"
    assert row1["DP"] == "42" and row1["GENEINFO"] == "BRCA1:672"
    # >6 significant digits survive ('%g' over float32 gave "1234.57")
    assert row1["VQSLOD"] == "1234.5678"
    assert row1["NEGATIVE_TRAIN_SITE"] is True
    assert row1["MYSTERY"] == "7"


def test_legacy_store_filter_with_duplicate_positions(tmp_path):
    """Predicate on a legacy store (no variantIdx column) must select
    exactly the matching rows even when positions repeat (split
    multiallelics) — identity-key matching would over-select."""
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from adam_tpu.api.datasets import GenotypeDataset
    from adam_tpu.io import parquet as pio

    vcf = tmp_path / "m.vcf"
    vcf.write_text("\n".join([
        "##fileformat=VCFv4.1",
        "##contig=<ID=chr1,length=1000>",
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\ts1",
        "chr1\t101\t.\tA\tG,T\t10\tPASS\t.\tGT\t1/2",
        "chr1\t201\t.\tC\tT\t90\tPASS\t.\tGT\t0/1",
    ]) + "\n")
    out = str(tmp_path / "g.adam")
    GenotypeDataset.load(str(vcf)).save(out)
    # strip variantIdx to simulate a legacy store
    import os

    vp = os.path.join(out, "variants.parquet")
    t = pq.read_table(vp)
    t2 = t.drop_columns(["variantIdx"])
    pq.write_table(t2, vp)

    full_v, full_g, _ = pio.load_genotypes(out)
    v, g, _ = pio.load_genotypes(out, filters=pc.field("qual") > 50)
    assert len(v.start) == 1 and int(v.start[0]) == 200
    # only the surviving variant's genotypes, re-indexed in range
    assert (g.variant_idx < len(v.start)).all()
    assert len(g.variant_idx) == int(
        (full_v.start[full_g.variant_idx] == 200).sum()
    )


def test_annotation_missing_marker_and_projection_completeness(tmp_path):
    """'.' (VCF missing marker) and unparseable values for known keys
    stay in the generic map and round-trip; projecting 'annotations'
    must pull the typed ann_* columns too."""
    from adam_tpu.api.datasets import GenotypeDataset
    from adam_tpu.formats.annotations import split_typed
    from adam_tpu.io import parquet as pio

    typed, rest = split_typed([{"MQ": ".", "DP": "bogus", "QD": "3.5"}])
    assert rest[0] == {"MQ": ".", "DP": "bogus"}
    assert typed["variantQualityByDepth"][0] == 3.5

    vcf = tmp_path / "m.vcf"
    vcf.write_text("\n".join([
        "##fileformat=VCFv4.1",
        "##contig=<ID=chr1,length=1000>",
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO",
        "chr1\t11\t.\tA\tG\t50\tPASS\tMQ=.;DP=42;XX=1",
    ]) + "\n")
    out = str(tmp_path / "g.adam")
    GenotypeDataset.load(str(vcf)).save(out)  # must not raise on 'MQ=.'
    v, _g, _sd = pio.load_genotypes(out, projection=["annotations"])
    assert v.sidecar.info[0] == {"MQ": ".", "DP": "42", "XX": "1"}
