"""Packed-column pass C: on-device packing, zero-copy Arrow assembly,
and the adaptive sharded writer pool.

The acceptance contract (ISSUE 12): Parquet parts written through the
packed path are **byte-identical** to the legacy matrix path across
compressions, window shapes and backends (pool-device / mesh / host
fallback), the pack kernels are bit-parity twins of their numpy
counterparts, and the writer pool keeps its crash-consistency and
gauge contracts under K-way write sharding and adaptive growth.
"""

import hashlib
import importlib.machinery
import os
import sys
import threading

import numpy as np
import pyarrow as pa
import pytest

from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.io import parquet
from adam_tpu.io.arrow_pack import (
    PackedQuals,
    index_name_array,
    pack_matrix_host,
    packed_qual_array,
)
from adam_tpu.io.sam import SamHeader
from adam_tpu.models.dictionaries import (
    RecordGroup,
    RecordGroupDictionary,
    SequenceDictionary,
    SequenceRecord,
)
from adam_tpu.ops import colpack
from adam_tpu.pipelines import bqsr as bq
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)

SD = SequenceDictionary((SequenceRecord("0", 1000),))
RGD = RecordGroupDictionary((RecordGroup("rg1"),))


# ---------------------------------------------------------------------------
# colpack kernels vs numpy twins
# ---------------------------------------------------------------------------
def test_pack_rows_kernel_matches_np():
    rng = np.random.default_rng(7)
    for n, w in ((1, 1), (5, 8), (64, 33), (128, 100)):
        mat = rng.integers(0, 256, (n, w)).astype(np.uint8)
        lens = rng.integers(0, w + 1, n).astype(np.int64)
        lens[:: max(1, n // 3)] = 0  # sprinkle empty rows
        total = int(lens.sum())
        size = n * w
        dev = np.asarray(colpack.pack_rows_kernel(mat, lens, size))
        host = colpack.pack_rows_np(mat, lens)
        assert dev.shape == (size,)
        np.testing.assert_array_equal(dev[:total], host)
        # the tail beyond the payload is zero fill, never read data
        assert not dev[total:].any() or total == size


def test_pack_rows_empty():
    out = colpack.pack_rows_np(np.zeros((0, 4), np.uint8), np.zeros(0))
    assert out.size == 0
    dev = np.asarray(
        colpack.pack_rows_kernel(
            np.zeros((1, 4), np.uint8), np.zeros(1, np.int64), 4
        )
    )
    assert dev.shape == (4,) and not dev.any()


def test_sanger_body_matches_lut():
    q = np.arange(256, dtype=np.uint8).reshape(16, 16)
    dev = np.asarray(colpack.sanger_body(q))
    np.testing.assert_array_equal(dev, schema.QUAL_SANGER_LUT256[q])


def test_fetch_grid_properties():
    for n in (1, 100, 4095, 4096, 4097, 123457, 10_000_000):
        g = colpack.fetch_grid(n)
        assert g >= n
        assert g >= 4096
        # over-fetch strictly bounded: < 1/16 of scale + quantum floor
        assert g - n < max(4096, 1 << max(0, n.bit_length() - 4)) + 1
    # bucketing collapses nearby sizes to one shape
    assert colpack.fetch_grid(1_000_001) == colpack.fetch_grid(1_000_002)


def test_packed_columns_enabled(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_PACKED_COLS", raising=False)
    assert colpack.packed_columns_enabled(True) is True
    assert colpack.packed_columns_enabled(False) is False
    for v, want in (("1", True), ("on", True), ("0", False),
                    ("off", False), ("auto", False)):
        monkeypatch.setenv("ADAM_TPU_PACKED_COLS", v)
        assert colpack.packed_columns_enabled(False) is want
    monkeypatch.setenv("ADAM_TPU_PACKED_COLS", "sideways")
    assert colpack.packed_columns_enabled(True) is True  # warn + default


# ---------------------------------------------------------------------------
# Arrow builders
# ---------------------------------------------------------------------------
def test_index_name_array_matches_legacy():
    names = ["chr17", "", "µ-contig", "chr20"]
    idx = np.array([0, -1, 2, 3, 3, 1, -1, 0], np.int32)
    got = index_name_array(idx, names)
    lut = np.array(names + [None], dtype=object)
    want = pa.array(lut[np.where(idx >= 0, idx, len(names))], pa.string())
    assert got.type == want.type == pa.string()
    assert got.equals(want)
    # all-valid fast path (no validity buffer)
    got2 = index_name_array(np.array([1, 1, 0]), names)
    assert got2.null_count == 0
    assert got2.to_pylist() == ["", "", "chr17"]
    # empty dictionary / empty column
    assert index_name_array(np.zeros(0, np.int32), []).to_pylist() == []


def test_packed_quals_take():
    lens = np.array([3, 0, 2, 0, 4], np.int64)
    buf = np.arange(9, dtype=np.uint8)
    p = PackedQuals(buf, lens)
    # dropping only zero-length rows: the buffer is shared, not copied
    q = p.take(np.array([0, 2, 4]))
    assert q.buf is p.buf
    np.testing.assert_array_equal(q.lens, [3, 2, 4])
    # dropping a byte-bearing row falls back to the span gather
    r = p.take(np.array([0, 4]))
    np.testing.assert_array_equal(r.lens, [3, 4])
    np.testing.assert_array_equal(r.buf, np.r_[buf[:3], buf[5:]])


def test_packed_qual_array_matches_decoded():
    rng = np.random.default_rng(3)
    n, w = 32, 20
    quals = rng.integers(0, 41, (n, w)).astype(np.uint8)
    lens = rng.integers(0, w + 1, n).astype(np.int64)
    has_qual = rng.random(n) < 0.8
    pack_lens = np.where(has_qual, lens, 0)
    packed = pack_matrix_host(quals, pack_lens, schema.QUAL_SANGER_LUT256)
    got = packed_qual_array(packed, has_qual)
    from adam_tpu.formats.strings import StringColumn

    want = StringColumn.from_matrix(
        schema.QUAL_SANGER_LUT256[quals], pack_lens, has_qual.copy()
    ).to_arrow()
    assert got.equals(want)


# ---------------------------------------------------------------------------
# End-to-end byte identity: packed vs matrix Parquet parts
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def wgs_apply_case(tmp_path_factory):
    """A trimmed-length WGS-shaped window + its solved recalibration
    table (numpy observe/solve — the differential oracle)."""
    from make_wgs_sam import make_wgs

    from adam_tpu.api.datasets import AlignmentDataset

    d = tmp_path_factory.mktemp("arrowpack")
    sam = str(d / "w.sam")
    make_wgs(sam, 3000, read_len=60, seed=11, n_contigs=2,
             contig_len=60_000, trimmed_frac=0.5, trimmed_min=20,
             trimmed_max=30)
    ds = AlignmentDataset.load(sam)
    total, mism, _rg, gl = bq._observe_device(ds, backend="numpy")
    table = bq.solve_recalibration_table(total, mism)
    return ds, np.ascontiguousarray(table, np.uint8), gl


def _sha(path):
    return hashlib.sha256(open(path, "rb").read()).hexdigest()


def _write_part(tmp_path, tag, ds, compression, packed=None):
    path = str(tmp_path / f"part-{tag}.parquet")
    table = parquet.to_arrow_alignments(
        ds.batch, ds.sidecar, ds.header, packed=packed
    )
    parquet._write_encoded(table, path, compression)
    return path


@pytest.mark.parametrize("compression", ["none", "snappy", "zstd"])
def test_packed_part_byte_identical_pool_device(
    wgs_apply_case, tmp_path, compression
):
    import jax

    ds, table, gl = wgs_apply_case
    ref = bq.apply_recalibration(ds, table, gl, "numpy")
    handle = bq.apply_recalibration_dispatch(
        ds, table, gl, "device", device=jax.local_devices()[0], pack=True
    )
    got, packed = bq.apply_recalibration_finish_packed(handle)
    assert packed is not None
    assert int(packed.lens.sum()) == len(packed.buf)
    a = _write_part(tmp_path, f"ref-{compression}", ref, compression)
    b = _write_part(
        tmp_path, f"packed-{compression}", got, compression, packed=packed
    )
    assert _sha(a) == _sha(b)


def test_packed_part_byte_identical_mesh(wgs_apply_case, tmp_path):
    import jax

    from adam_tpu.parallel.partitioner import MeshPartitioner

    ds, table, gl = wgs_apply_case
    ref = bq.apply_recalibration(ds, table, gl, "numpy")
    mp = MeshPartitioner(jax.local_devices()[:2])
    handle = bq.apply_recalibration_dispatch(
        ds, mp.put_replicated(table), gl, "device", mesh=mp, pack=True
    )
    got, packed = bq.apply_recalibration_finish_packed(handle)
    assert packed is not None
    a = _write_part(tmp_path, "mesh-ref", ref, "zstd")
    b = _write_part(tmp_path, "mesh-packed", got, "zstd", packed=packed)
    assert _sha(a) == _sha(b)


def test_packed_part_byte_identical_host_fallback(wgs_apply_case, tmp_path):
    """The host path (device lost / degrade) writes through packed=None
    and must equal the packed output too — the replay contract."""
    import jax

    ds, table, gl = wgs_apply_case
    ref = bq.apply_recalibration(ds, table, gl, "numpy")
    handle = bq.apply_recalibration_dispatch(
        ds, table, gl, "device", device=jax.local_devices()[0], pack=True
    )
    got, packed = bq.apply_recalibration_finish_packed(handle)
    a = _write_part(tmp_path, "host", ref, "zstd")
    b = _write_part(tmp_path, "dev", got, "zstd", packed=packed)
    assert _sha(a) == _sha(b)


def _read(ref, start, L=8, name=None):
    seq = "ACGTACGT"[:L]
    return {
        "name": name or f"r{start}", "flags": 0, "contig_idx": 0,
        "start": start, "mapq": 60, "cigar": f"{L}M", "seq": seq,
        "qual": "I" * L, "mate_contig_idx": -1, "mate_start": -1,
        "tlen": 0, "read_group_idx": 0, "attrs": "", "md": str(L),
    }


def test_packed_part_max_length_and_invalid_rows(tmp_path):
    """Full-width rows (lens == lmax, the uniform fast path) plus
    invalid padding rows: the compaction drops them for free on the
    packed side (they carry no bytes)."""
    recs = [_read("0", 10 + i) for i in range(5)]
    batch, side = pack_reads(recs, round_rows_to=8)  # 3 invalid pad rows
    header = SamHeader(seq_dict=SD, read_groups=RGD)
    from adam_tpu.api.datasets import AlignmentDataset

    ds = AlignmentDataset(batch, side, header)
    b = batch.to_numpy()
    pack_lens = colpack.pack_lengths(b.lengths, b.valid, b.has_qual)
    assert (b.lengths[np.asarray(b.valid)] == b.lmax).all()
    packed = pack_matrix_host(
        np.asarray(b.quals), pack_lens, schema.QUAL_SANGER_LUT256
    )
    a = _write_part(tmp_path, "ml-ref", ds, "zstd")
    bpath = _write_part(tmp_path, "ml-packed", ds, "zstd", packed=packed)
    assert _sha(a) == _sha(bpath)


def test_packed_part_empty_window(tmp_path):
    """A window with zero rows encodes identically with and without a
    (vacuous) packed payload."""
    batch, side = pack_reads([])
    header = SamHeader(seq_dict=SD, read_groups=RGD)
    from adam_tpu.api.datasets import AlignmentDataset

    ds = AlignmentDataset(batch, side, header)
    packed = PackedQuals(np.zeros(0, np.uint8), np.zeros(0, np.int64))
    a = _write_part(tmp_path, "empty-ref", ds, "zstd")
    b = _write_part(tmp_path, "empty-packed", ds, "zstd", packed=packed)
    assert _sha(a) == _sha(b)


# ---------------------------------------------------------------------------
# Adaptive sharded writer pool
# ---------------------------------------------------------------------------
def test_sharded_writer_pool_roundtrip(tmp_path):
    recs = [_read("0", 10 + i) for i in range(4)]
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=SD, read_groups=RGD)
    out = tmp_path / "parts"
    out.mkdir()
    published = []
    pool = parquet.PartWriterPool(
        n_encoders=2, inflight_parts=2, n_io=3, adaptive=False,
        on_published=published.append,
    )
    paths = [str(out / parquet.part_name(i)) for i in range(7)]
    for p in paths:
        pool.submit(p, batch, side, header)
    pool.close()
    assert sorted(published) == sorted(paths)
    for p in paths:
        back, _s, _h = parquet.load_alignments(p)
        assert back.n_rows == batch.n_rows
    assert not (out / parquet.TMP_DIR_NAME).exists()


def test_sharded_writer_pool_error_failfast(tmp_path):
    recs = [_read("0", 10)]
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=SD, read_groups=RGD)
    pool = parquet.PartWriterPool(
        n_encoders=1, inflight_parts=1, n_io=2, adaptive=False
    )
    pool.submit(
        str(tmp_path / "missing" / "part-r-00000.parquet"),
        batch, side, header,
    )
    with pytest.raises(Exception):
        pool.close()


def test_writer_shards_resolution(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_WRITER_SHARDS", raising=False)
    assert 1 <= parquet.resolve_writer_shards() <= 2
    assert parquet.resolve_writer_shards(5) == 5
    assert parquet.resolve_writer_shards(99) == 8  # clamped
    monkeypatch.setenv("ADAM_TPU_WRITER_SHARDS", "3")
    assert parquet.resolve_writer_shards() == 3
    monkeypatch.setenv("ADAM_TPU_WRITER_SHARDS", "soup")
    assert 1 <= parquet.resolve_writer_shards() <= 2  # warn + default


def test_writer_adaptive_env(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_WRITER_ADAPTIVE", raising=False)
    assert parquet.writer_adaptive_enabled(True) is True
    monkeypatch.setenv("ADAM_TPU_WRITER_ADAPTIVE", "0")
    assert parquet.writer_adaptive_enabled(True) is False
    monkeypatch.setenv("ADAM_TPU_WRITER_ADAPTIVE", "1")
    assert parquet.writer_adaptive_enabled(False) is True


def test_adaptive_growth_bounded():
    pool = parquet.PartWriterPool(
        n_encoders=1, inflight_parts=1, adaptive=True, n_io=1,
        tracer=tele.Tracer(recording=True),
    )
    cap = pool._bound_cap
    assert cap >= 2  # affinity floor + io thread
    for _ in range(50):
        pool._maybe_grow(True)
    assert pool.inflight_bound == cap  # grew, then stopped at the cap
    fixed = parquet.PartWriterPool(
        n_encoders=1, inflight_parts=1, adaptive=False, n_io=1
    )
    for _ in range(50):
        fixed._maybe_grow(True)
    assert fixed.inflight_bound == 1
    pool.close()
    fixed.close()


def test_adaptive_growth_needs_sustained_gating():
    pool = parquet.PartWriterPool(
        n_encoders=1, inflight_parts=1, adaptive=True, n_io=1
    )
    start = pool.inflight_bound
    # isolated gated submits interleaved with fast ones never trip it
    for _ in range(8):
        pool._maybe_grow(True)
        pool._maybe_grow(False)
        pool._maybe_grow(False)
        pool._maybe_grow(False)
    assert pool.inflight_bound == start
    pool.close()


def test_depth_gauge_ordered_and_never_negative():
    """The queue-depth gauge is written under the depth lock: under a
    concurrent +1/-1 storm from K threads its samples can never go
    negative and the LAST sample equals the true final depth (0)."""
    tr = tele.Tracer(recording=True)
    pool = parquet.PartWriterPool(
        n_encoders=1, inflight_parts=4, n_io=2, adaptive=False, tracer=tr
    )

    def storm():
        for _ in range(200):
            pool._sample_depth(+1)
            pool._sample_depth(-1)

    threads = [threading.Thread(target=storm) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    g = tr.snapshot()["gauges"][tele.G_POOL_DEPTH]
    assert g["min"] >= 0
    assert g["last"] == 0
    pool.close()


def test_encode_byte_counters(tmp_path):
    recs = [_read("0", 10 + i) for i in range(4)]
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=SD, read_groups=RGD)
    tr = tele.Tracer(recording=True)
    pool = parquet.PartWriterPool(
        n_encoders=1, inflight_parts=1, n_io=1, adaptive=False, tracer=tr
    )
    pool.submit(str(tmp_path / parquet.part_name(0)), batch, side, header)
    pool.close()
    c = tr.snapshot()["counters"]
    assert c[tele.C_ENCODE_BYTES_IN] > 0
    assert c[tele.C_ENCODE_BYTES_OUT] > 0


# ---------------------------------------------------------------------------
# bench-diff derived stage keys
# ---------------------------------------------------------------------------
def _load_bench_diff():
    path = os.path.join(
        os.path.dirname(__file__), "..", "scripts", "bench-diff"
    )
    loader = importlib.machinery.SourceFileLoader("bench_diff_mod", path)
    return loader.load_module()


def test_bench_diff_stage_keys(tmp_path):
    bd = _load_bench_diff()
    snap = {
        "spans": {
            "streamed.pass_c": {"total_s": 5.0},
            "streamed.apply.dispatch": {"total_s": 1.0},
            "streamed.apply.fetch": {"total_s": 0.5},
            "device.pool.prewarm.pass_c": {"total_s": 0.5},
            "streamed.write_wait": {"total_s": 2.0},
        },
        "counters": {},
        "device_spans": {},
    }
    keys = bd._collect_snapshot(snap)
    assert keys["stages.apply_split_s"] == (3.0, "lower")
    assert keys["stages.apply_split_plus_write_wait_s"] == (5.0, "lower")
    # the require-factor gate consumes the combined key end to end
    import json

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(snap))
    fast = json.loads(json.dumps(snap))
    for name in fast["spans"]:
        fast["spans"][name]["total_s"] /= 10.0
    new.write_text(json.dumps(fast))
    rc = bd.main([
        str(old), str(new), "--json",
        "--require-factor", "stages.apply_split_plus_write_wait_s=5",
    ])
    assert rc == 0


def test_bench_diff_baseline_dir(tmp_path, capsys):
    """--baseline-dir picks the newest BENCH_r*.json (round number
    wins, mtime breaks ties) so CI never hardcodes the old filename;
    naming the baseline both ways (or neither) is a usage error."""
    import json

    bd = _load_bench_diff()
    snap = {
        "spans": {"streamed.pass_c": {"total_s": 5.0}},
        "counters": {},
        "device_spans": {},
    }
    rounds = tmp_path / "rounds"
    rounds.mkdir()
    (rounds / "BENCH_r1.json").write_text(json.dumps(snap))
    (rounds / "BENCH_r10_gpu.json").write_text(json.dumps(snap))
    (rounds / "notes.json").write_text("{}")  # never a candidate
    assert bd.newest_bench_artifact(str(rounds)).endswith(
        "BENCH_r10_gpu.json")

    new = tmp_path / "new.json"
    new.write_text(json.dumps(snap))
    assert bd.main([str(new), "--baseline-dir", str(rounds)]) == 0
    capsys.readouterr()

    # both spellings at once, or neither: usage error, not a diff
    assert bd.main([str(rounds / "BENCH_r1.json"), str(new),
                    "--baseline-dir", str(rounds)]) == 2
    assert bd.main([str(new)]) == 2
    assert "exactly one way" in capsys.readouterr().err
    # an empty dir is a clean error, not a traceback
    empty = tmp_path / "empty"
    empty.mkdir()
    assert bd.main([str(new), "--baseline-dir", str(empty)]) != 0
