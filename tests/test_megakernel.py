"""Megakernel tier (ISSUE 18, docs/PERF.md "Megakernel tier"): the
fused B→C dispatch and the Pallas kernel backend.

The matrix this file owes the acceptance criteria:

* toggle parsing — `ADAM_TPU_FUSED_BC` through the shared env_toggle,
  `ADAM_TPU_KERNEL_BACKEND` through the selector's warn-and-default
  contract (explicit arg > backend_scope > env);
* kernel-level bit parity — `fused_bc_body` vs the separate
  observe_packed + apply_pack2 passes (including the wider merged-table
  geometry), and pallas-vs-XLA for every ported inner loop (interpret
  mode off-TPU);
* the compile-ledger backend key — flipping the backend makes the same
  (kernel, shape, device) a fresh miss;
* end-to-end byte parity of known-table runs, fused vs unfused, across
  pool/mesh and 1/2/8 virtual devices, with the dispatch-count factor
  (≥ 1.5x), `device.windows.fused` and the `streamed.fused_bc` /
  `kernel.backend` gauges asserted, `device.compile.in_window == 0`;
* the fault matrix — eviction mid-fused-dispatch replays through the
  split chain byte-identically, and a SIGKILL mid-fused run resumes
  byte-identically (`proc.kill device=fused_bc`);
* the kernelbench schema (`adam_tpu.kernelbench/1`) and the analyzer's
  merged `fused_bc_apply` stage row.
"""

import hashlib
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from adam_tpu.ops.kernel_backend import backend_scope, kernel_backend
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


def _sha_parts(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in os.listdir(d) if f.startswith("part-")
    }


# ---------------------------------------------------------------------------
# Toggle parsing and backend resolution
# ---------------------------------------------------------------------------
def test_fused_bc_toggle_parsing(monkeypatch):
    from adam_tpu.pipelines.bqsr import fused_bc_enabled

    monkeypatch.delenv("ADAM_TPU_FUSED_BC", raising=False)
    assert fused_bc_enabled() is True
    assert fused_bc_enabled(default=False) is False
    for raw, want in (("1", True), ("on", True), ("0", False),
                      ("off", False), ("auto", True)):
        monkeypatch.setenv("ADAM_TPU_FUSED_BC", raw)
        assert fused_bc_enabled() is want, raw


def test_kernel_backend_resolution(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_KERNEL_BACKEND", raising=False)
    assert kernel_backend() == "xla"
    for raw, want in (("", "xla"), ("auto", "xla"), ("xla", "xla"),
                      ("pallas", "pallas"), ("PALLAS", "pallas")):
        monkeypatch.setenv("ADAM_TPU_KERNEL_BACKEND", raw)
        assert kernel_backend() == want, raw
    # an env typo warns once and falls back (long runs must not die)
    monkeypatch.setenv("ADAM_TPU_KERNEL_BACKEND", "bogus")
    assert kernel_backend() == "xla"
    # scope beats env; explicit argument beats both
    with backend_scope("pallas"):
        assert kernel_backend() == "pallas"
        assert kernel_backend("xla") == "xla"
        with backend_scope("xla"):
            assert kernel_backend() == "xla"
        assert kernel_backend() == "pallas"
    # a typo in CODE is a bug: explicit override raises
    with pytest.raises(ValueError):
        kernel_backend("tpu")
    with pytest.raises(ValueError):
        with backend_scope("mosaic"):
            pass


def test_compile_ledger_keys_on_backend():
    """The PR 18 key fix: an XLA-warmed (kernel, shape, device) says
    nothing about the pallas executable of the same shape — flipping
    the backend must make the triple a fresh miss."""
    from adam_tpu.utils import compile_ledger as cl

    key = ("test.backend_key", 64, 64)
    tele.TRACE.reset()
    tele.TRACE.recording = True
    try:
        with cl.track(key, "test-dev"):
            pass
        with cl.track(key, "test-dev"):
            pass
        with backend_scope("pallas"):
            with cl.track(key, "test-dev"):
                pass
        snap = tele.TRACE.snapshot()
    finally:
        tele.TRACE.recording = False
    c = snap["counters"]
    assert c.get(tele.C_COMPILE_MISSES, 0) == 2
    assert c.get(tele.C_COMPILE_HITS, 0) == 1


# ---------------------------------------------------------------------------
# Kernel-level bit parity: fused vs separate, pallas vs XLA
# ---------------------------------------------------------------------------
def _fused_inputs(seed=5, g=48, gl=40, n_rg=3, n_cyc=None):
    from adam_tpu.ops.colpack import pack_mask_bits

    rng = np.random.default_rng(seed)
    return dict(
        g=g, gl=gl, n_rg=n_rg,
        bases=rng.integers(0, 6, (g, gl)).astype(np.uint8),
        quals=rng.integers(0, 60, (g, gl)).astype(np.uint8),
        lengths=rng.integers(1, gl, g).astype(np.int32),
        flags=rng.integers(0, 4, g).astype(np.int32),
        rg=rng.integers(-1, n_rg - 1, g).astype(np.int32),
        res_bits=pack_mask_bits(rng.random((g, gl)) < 0.6),
        mm_bits=pack_mask_bits(rng.random((g, gl)) < 0.2),
        read_ok=rng.random(g) < 0.8,
        has_qual=rng.random(g) < 0.9,
        valid=rng.random(g) < 0.95,
        table=rng.integers(
            2, 43, (n_rg, 94, n_cyc or 2 * gl + 1, 17)
        ).astype(np.uint8),
    )


def _run_fused(k):
    from adam_tpu.pipelines.bqsr import jit_variant

    size = k["g"] * k["gl"]
    return tuple(
        np.asarray(a) for a in jit_variant("fused_bc", False)(
            k["bases"], k["quals"], k["lengths"], k["flags"], k["rg"],
            k["res_bits"], k["mm_bits"], k["read_ok"], k["has_qual"],
            k["valid"], k["table"], k["n_rg"], k["gl"], size,
        )
    )


def _run_separate(k):
    from adam_tpu.pipelines.bqsr import jit_variant

    size = k["g"] * k["gl"]
    total, mism = jit_variant("observe_packed", False)(
        k["bases"], k["quals"], k["lengths"], k["flags"], k["rg"],
        k["res_bits"], k["mm_bits"], k["read_ok"], k["n_rg"], k["gl"],
    )
    pq, pb = jit_variant("apply_pack2", False)(
        k["bases"], k["quals"], k["lengths"], k["flags"], k["rg"],
        k["has_qual"], k["valid"], k["table"], k["gl"], size,
    )
    return tuple(np.asarray(a) for a in (total, mism, pq, pb))


def test_fused_bc_kernel_bit_parity():
    """The megakernel is a pure composition: its four outputs are
    bitwise the separate observe + apply_pack2 outputs."""
    k = _fused_inputs()
    for got, want in zip(_run_fused(k), _run_separate(k)):
        np.testing.assert_array_equal(got, want)
    assert int(_run_fused(k)[0].sum()) > 0  # a real workload


def test_fused_bc_wider_table_parity():
    """Known-sites tables carry the COHORT's cycle-axis width, wider
    than this window's — the centered gather must agree with the
    separate apply against the same wide table."""
    k = _fused_inputs(seed=9, gl=32, n_cyc=2 * 48 + 1)
    for got, want in zip(_run_fused(k), _run_separate(k)):
        np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("g,gl", [(16, 24), (48, 40), (96, 96)])
def test_pallas_vs_xla_kernel_parity(g, gl):
    """Every Pallas-ported inner loop is bit-parity with its XLA body
    (interpret mode off-TPU), across non-multiple-of-block grids."""
    from adam_tpu.ops.colpack import pack_rows_kernel

    k = _fused_inputs(seed=11 + g, g=g, gl=gl)
    lens = np.where(
        k["valid"], k["lengths"].astype(np.int64), 0
    )
    out = {}
    for bk in ("xla", "pallas"):
        with backend_scope(bk):
            out[bk] = (
                _run_fused(k)
                + _run_separate(k)
                + (np.asarray(
                    pack_rows_kernel(k["quals"], lens, g * gl)
                ),)
            )
    for got, want in zip(out["pallas"], out["xla"]):
        np.testing.assert_array_equal(got, want)


def test_kernelbench_schema_and_backends():
    """The microbench document: stable schema, every kernel timed under
    both backends, pallas rows marked interpret off-TPU, no error
    rows."""
    from adam_tpu.utils.kernelbench import (
        KERNELS, SCHEMA, run_kernelbench,
    )

    doc = run_kernelbench(grids=((32, 32),), iters=1)
    assert doc["schema"] == SCHEMA
    rows = doc["rows"]
    bad = [r for r in rows if "error" in r]
    assert not bad, bad
    for kern in KERNELS:
        backs = {r["backend"] for r in rows if r["kernel"] == kern}
        assert backs == {"xla", "pallas"}, kern
    if doc["jax_backend"] != "tpu":
        assert all(
            r["mode"] == "interpret"
            for r in rows if r["backend"] == "pallas"
        )
    for r in rows:
        assert r["mean_s"] >= r["best_s"] > 0, r


# ---------------------------------------------------------------------------
# End-to-end: known-table byte parity + the dispatch-count factor
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def megakernel_runs(tmp_path_factory):
    """One input, one discovered table, then known-table streamed runs:
    unfused (the A/B reference), fused across pool/mesh/1-dev/8-dev, a
    pallas-backend fused leg, and an eviction-mid-fused leg."""
    from make_wgs_sam import make_wgs

    from adam_tpu.pipelines.streamed import transform_streamed

    d = tmp_path_factory.mktemp("megakernel")
    path = str(d / "in.sam")
    make_wgs(path, 4500, 100, n_contigs=2, contig_len=30_000,
             indel_every=700, snp_every=400)

    from adam_tpu.utils import faults

    runs = {}

    def leg(label, mode, n, fused, extra=None, known=None):
        out = str(d / f"out.{label}.adam")
        env_keys = {"ADAM_TPU_RESIDENT": "1",
                    "ADAM_TPU_FUSED_BC": fused, **(extra or {})}
        old = {k: os.environ.get(k) for k in env_keys}
        os.environ.update(env_keys)
        if mode is not None:
            os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
        faults.install((extra or {}).get("ADAM_TPU_FAULTS"))
        tele.TRACE.reset()
        tele.TRACE.recording = True
        try:
            stats = transform_streamed(
                path, out, window_reads=2048, devices=n,
                partitioner=mode, known_table=known,
                run_dir=str(d / f"rd.{label}"),
            )
            snap = tele.TRACE.snapshot()
        finally:
            tele.TRACE.recording = False
            faults.install(None)
            os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        runs[label] = (out, stats, snap)

    # the discovered-table reference (no table at ingest: never fuses)
    leg("discover", "pool", 2, "1")
    with np.load(str(d / "rd.discover" / "table.npz")) as z:
        known = (np.asarray(z["table"], np.uint8), int(z["gl"]))

    leg("unfused", "pool", 2, "0", known=known)
    leg("fused_pool", "pool", 2, "1", known=known)
    leg("fused_mesh", "mesh", 2, "1", known=known)
    leg("fused_1dev", "pool", 1, "1", known=known)
    leg("fused_8dev", "pool", 8, "1", known=known)
    leg("fused_pallas", "pool", 2, "1", known=known,
        extra={"ADAM_TPU_KERNEL_BACKEND": "pallas"})
    # a device dies mid-fused-dispatch: the resident handle drops and
    # the replay falls back to the split chain from the host copy
    leg("fused_evict", "pool", 2, "1", known=known, extra={
        "ADAM_TPU_FAULTS": "device.dispatch=permanent,device=1,after=1",
        "ADAM_TPU_RETRY_BACKOFF_S": "0.001",
        "ADAM_TPU_RETRY_ATTEMPTS": "2",
    })
    return runs


def test_megakernel_parts_bit_identical_across_matrix(megakernel_runs):
    ref = _sha_parts(megakernel_runs["unfused"][0])
    assert ref
    for label in ("discover", "fused_pool", "fused_mesh", "fused_1dev",
                  "fused_8dev", "fused_pallas", "fused_evict"):
        assert _sha_parts(megakernel_runs[label][0]) == ref, label


def test_megakernel_dispatch_factor(megakernel_runs):
    """The tier's headline: fused known-table runs dispatch ≥ 1.5x
    fewer per-window device calls than the unfused chain, with every
    fused window counted and zero in-window cold compiles."""
    _, st_u, sn_u = megakernel_runs["unfused"]
    _, st_f, sn_f = megakernel_runs["fused_pool"]
    assert st_f["fused_bc"] is True
    assert st_u["fused_bc"] is False
    assert megakernel_runs["discover"][1]["fused_bc"] is False
    c_u, c_f = sn_u["counters"], sn_f["counters"]
    assert c_f.get(tele.C_FUSED_DISPATCHED, 0) > 0
    assert tele.C_FUSED_DISPATCHED not in c_u
    d_u = c_u[tele.C_DEVICE_DISPATCHED]
    d_f = c_f[tele.C_DEVICE_DISPATCHED]
    assert d_u / d_f >= 1.5, (d_u, d_f)
    for label in ("unfused", "fused_pool", "fused_mesh", "fused_8dev"):
        snap = megakernel_runs[label][2]
        assert snap["counters"].get(
            tele.C_COMPILE_IN_WINDOW, 0
        ) == 0, label
    assert sn_f["gauges"][tele.G_FUSED_BC]["last"] == 1
    assert sn_u["gauges"][tele.G_FUSED_BC]["last"] == 0
    assert sn_f["gauges"][tele.G_KERNEL_BACKEND]["last"] == 0
    assert megakernel_runs["fused_pallas"][2]["gauges"][
        tele.G_KERNEL_BACKEND
    ]["last"] == 1


def test_megakernel_mesh_counts_fused(megakernel_runs):
    _, st_m, sn_m = megakernel_runs["fused_mesh"]
    assert st_m["fused_bc"] is True
    c = sn_m["counters"]
    assert c.get(tele.C_FUSED_DISPATCHED, 0) > 0
    assert c.get(tele.C_MESH_DISPATCHED, 0) > 0


def test_megakernel_eviction_falls_back_to_split(megakernel_runs):
    """Byte-identity is asserted in the matrix test; here the shape of
    the recovery: the chip evicted, its windows' fused handles gone,
    and the run still finished (replayed windows take the split
    chain — fused_bc_dispatch declines a dead resident handle)."""
    _, stats, snap = megakernel_runs["fused_evict"]
    c = snap["counters"]
    assert c.get(tele.C_DEVICE_EVICTED, 0) >= 1
    assert stats["fused_bc"] is True


def test_analyzer_merges_fused_stage(megakernel_runs):
    """`adam-tpu analyze` on a fused run renders observe + pass-C apply
    as ONE `fused_bc_apply` stage row (the two spans no longer describe
    disjoint dispatch chains); fractions still sum against run wall."""
    from adam_tpu.utils import analyzer

    rep_f = analyzer.analyze(megakernel_runs["fused_pool"][2])
    stages_f = rep_f["stages"]
    assert "fused_bc_apply" in stages_f
    assert "observe" not in stages_f and "pass_c_apply" not in stages_f
    assert stages_f["fused_bc_apply"]["total_s"] >= 0
    fracs = [
        row.get("frac") for row in stages_f.values()
        if isinstance(row, dict) and row.get("frac") is not None
    ]
    assert fracs and sum(fracs) <= 1.05
    rep_u = analyzer.analyze(megakernel_runs["unfused"][2])
    assert "fused_bc_apply" not in rep_u["stages"]
    assert "observe" in rep_u["stages"]


# ---------------------------------------------------------------------------
# SIGKILL mid-fused-dispatch, then --resume
# ---------------------------------------------------------------------------
_KILL_DRIVER = (
    "import sys\n"
    "import numpy as np\n"
    "try:\n"
    "    import jax, jax._src.xla_bridge as xb\n"
    "    xb._backend_factories.pop('axon', None)\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "except Exception: pass\n"
    "from adam_tpu.pipelines.streamed import transform_streamed\n"
    "with np.load(sys.argv[5]) as z:\n"
    "    known = (np.asarray(z['table'], np.uint8), int(z['gl']))\n"
    "transform_streamed(sys.argv[1], sys.argv[2], window_reads=512,\n"
    "                   devices=2, known_table=known,\n"
    "                   run_dir=sys.argv[3], resume=sys.argv[4] == '1')\n"
)


@pytest.mark.slow
def test_megakernel_sigkill_mid_fused_then_resume(tmp_path):
    """SIGKILL at the fused-dispatch fault point (`proc.kill
    device=fused_bc`) with windows in flight, then --resume:
    byte-identical to an uninterrupted unfused run."""
    from make_wgs_sam import make_wgs

    from adam_tpu.pipelines.streamed import transform_streamed

    path = str(tmp_path / "in.sam")
    make_wgs(path, 2000, 100, n_contigs=2, contig_len=20_000,
             indel_every=700, snp_every=400)
    # discover the table, then an unfused known-table baseline
    disc = str(tmp_path / "disc.adam")
    transform_streamed(path, disc, window_reads=512,
                       run_dir=str(tmp_path / "rd.disc"))
    table_npz = str(tmp_path / "rd.disc" / "table.npz")
    with np.load(table_npz) as z:
        known = (np.asarray(z["table"], np.uint8), int(z["gl"]))
    clean = str(tmp_path / "clean.adam")
    old = os.environ.get("ADAM_TPU_FUSED_BC")
    os.environ["ADAM_TPU_FUSED_BC"] = "0"
    try:
        transform_streamed(path, clean, window_reads=512,
                           known_table=known)
    finally:
        if old is None:
            os.environ.pop("ADAM_TPU_FUSED_BC", None)
        else:
            os.environ["ADAM_TPU_FUSED_BC"] = old
    baseline = _sha_parts(clean)
    assert baseline

    out, rd = str(tmp_path / "out.adam"), str(tmp_path / "run")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=2"),
        "ADAM_TPU_NO_COMPILE_CACHE": "1",
        "ADAM_TPU_BQSR_BACKEND": "device",
        "ADAM_TPU_RESIDENT": "1",
        "ADAM_TPU_FUSED_BC": "1",
        "ADAM_TPU_FAULTS":
            "proc.kill=kill,device=fused_bc,after=1,times=1",
    })
    cwd = os.path.join(os.path.dirname(__file__), "..")
    rc = subprocess.run(
        [sys.executable, "-c", _KILL_DRIVER, path, out, rd, "0",
         table_npz],
        env=env, cwd=cwd,
    ).returncode
    assert rc == -signal.SIGKILL, f"expected SIGKILL, got {rc}"
    env.pop("ADAM_TPU_FAULTS")
    rc = subprocess.run(
        [sys.executable, "-c", _KILL_DRIVER, path, out, rd, "1",
         table_npz],
        env=env, cwd=cwd,
    ).returncode
    assert rc == 0
    assert _sha_parts(out) == baseline
