"""Device-vs-host bit-parity for the per-residue kernel backends.

The streamed flagship defaults to the ``device`` backend when a chip is
attached (``adam_tpu.pipelines.bqsr.bqsr_backend``): BQSR observe as a
jit scatter-add, BQSR apply as a jit table gather, markdup 5'-key/score
as jit reductions.  These tests pin every backend to the same bits —
the jit kernels run on the CPU jax backend here, so the *traced
programs* that ship to the chip are what is being differentially
tested, against the numpy twins and (where built) the native C++ walks.
"""

import itertools
import os
import sys

import numpy as np
import pytest

from adam_tpu.api.datasets import AlignmentDataset, GenotypeDataset
from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.io import load_alignments
from adam_tpu.io.sam import SamHeader
from adam_tpu.models.dictionaries import (
    RecordGroup,
    RecordGroupDictionary,
    SequenceDictionary,
    SequenceRecord,
)
from adam_tpu.pipelines import bqsr as bq
from adam_tpu.pipelines import markdup as md

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)

_counter = itertools.count()


# ---------------------------------------------------------------------------
# Backend resolution
# ---------------------------------------------------------------------------
def test_backend_env_override(monkeypatch):
    for b in bq.BACKENDS:
        monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", b)
        assert bq.bqsr_backend() == b
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "Device")  # case-folded
    assert bq.bqsr_backend() == "device"
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "spark")
    with pytest.raises(ValueError, match="spark"):
        bq.bqsr_backend()


def test_backend_explicit_override_beats_env(monkeypatch):
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "numpy")
    assert bq.bqsr_backend("device") == "device"


def test_backend_topology_default(monkeypatch):
    """Without a chip (the CPU test harness) the default must be a host
    backend; with one, device."""
    monkeypatch.delenv("ADAM_TPU_BQSR_BACKEND", raising=False)
    monkeypatch.setattr(bq, "_CHIP_PRESENT", False)
    assert bq.bqsr_backend() in ("native", "numpy")
    monkeypatch.setattr(bq, "_CHIP_PRESENT", True)
    assert bq.bqsr_backend() == "device"


# ---------------------------------------------------------------------------
# WGS-shaped differential fixtures
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def wgs_ds(tmp_path_factory):
    """Small WGS-shaped dataset + known-sites table: indels, soft clips,
    planted SNPs, duplicates — every covariate path exercised."""
    from make_wgs_sam import make_wgs

    d = tmp_path_factory.mktemp("parity")
    sam = str(d / "w.sam")
    vcf = str(d / "w.vcf")
    make_wgs(sam, 2048, 100, n_contigs=2, contig_len=40_000,
             indel_every=800, snp_every=400, known_sites_out=vcf)
    ds = load_alignments(sam)
    known = GenotypeDataset.load(
        vcf, contig_names=ds.seq_dict.names
    ).snp_table()
    return ds, known


def test_observe_device_matches_numpy_wgs(wgs_ds):
    """The jit scatter-add histogram (the chip observe pass) and the
    numpy bincount twin produce identical tables, known-site masking
    included."""
    ds, known = wgs_ds
    t_dev, m_dev, rg_dev, g_dev = bq._observe_device(ds, known, "device")
    t_np, m_np, rg_np, g_np = bq._observe_device(ds, known, "numpy")
    assert rg_dev == rg_np and g_dev == g_np
    np.testing.assert_array_equal(np.asarray(t_dev), t_np)
    np.testing.assert_array_equal(np.asarray(m_dev), m_np)
    assert int(t_np.sum()) > 0 and int(m_np.sum()) > 0


def test_observe_device_matches_native_wgs(wgs_ds):
    """Device scatter-add vs the threaded C++ MD-walk histogram."""
    from adam_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    ds, known = wgs_ds
    t_dev, m_dev, _, g_dev = bq._observe_device(ds, known, "device")
    t_nat, m_nat, _, g_nat = bq._observe_device(ds, known, "native")
    assert g_dev == g_nat
    np.testing.assert_array_equal(np.asarray(t_dev), t_nat)
    np.testing.assert_array_equal(np.asarray(m_dev), m_nat)


def test_apply_device_matches_host_wgs(wgs_ds):
    """The full observe->solve->apply pass is bit-identical across
    backends: recalibrated quals AND the stashed OQ sidecar."""
    ds, known = wgs_ds
    outs = {
        b: ds.recalibrate_base_qualities(known, backend=b)
        for b in ("device", "numpy")
    }
    ref = outs["numpy"].batch.to_numpy()
    assert (
        np.asarray(ref.quals) != np.asarray(ds.batch.to_numpy().quals)
    ).any(), "recalibration must change something for parity to mean anything"
    for b, out in outs.items():
        got = out.batch.to_numpy()
        np.testing.assert_array_equal(
            np.asarray(got.quals), np.asarray(ref.quals), err_msg=b
        )
        assert out.sidecar.orig_quals == outs["numpy"].sidecar.orig_quals


def test_apply_device_matches_native_wgs(wgs_ds):
    from adam_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    ds, known = wgs_ds
    dev = ds.recalibrate_base_qualities(known, backend="device")
    nat = ds.recalibrate_base_qualities(known, backend="native")
    np.testing.assert_array_equal(
        np.asarray(dev.batch.to_numpy().quals),
        np.asarray(nat.batch.to_numpy().quals),
    )
    assert dev.sidecar.orig_quals == nat.sidecar.orig_quals


def test_apply_dispatch_finish_split_equals_eager(wgs_ds):
    """The streamed pipeline's double-buffered split (dispatch window
    i+1 before finishing window i) must equal the eager single-call
    apply."""
    ds, known = wgs_ds
    total, mism, _rg, gl = bq._observe_device(ds, known, "numpy")
    table = bq.solve_recalibration_table(total, mism)
    eager = bq.apply_recalibration(ds, table, gl, "device")
    h1 = bq.apply_recalibration_dispatch(ds, table, gl, "device")
    h2 = bq.apply_recalibration_dispatch(ds, table, gl, "device")
    out1 = bq.apply_recalibration_finish(h1)
    out2 = bq.apply_recalibration_finish(h2)
    for out in (out1, out2):
        np.testing.assert_array_equal(
            np.asarray(out.batch.to_numpy().quals),
            np.asarray(eager.batch.to_numpy().quals),
        )


# ---------------------------------------------------------------------------
# Golden-fixture parity (reference tree, skips when absent)
# ---------------------------------------------------------------------------
def test_observe_device_matches_golden(ref_resources):
    """The device scatter-add observe pass reproduces the GATK-derived
    bqsr1-ref.observed table exactly (the reference's own golden test,
    BaseQualityRecalibrationSuite.scala:30-47, run against the chip
    kernel instead of the host walk)."""
    from adam_tpu.models.snp_table import SnpTable

    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    snps = SnpTable.from_file(str(ref_resources / "bqsr1.snps"))
    t, m, rg_names, gl = bq._observe_device(ds, snps, "device")
    obs = bq.ObservationTable(np.asarray(t), np.asarray(m), rg_names, gl)
    ours = sorted(l for l in obs.to_csv().split("\n") if l)
    golden = sorted(
        l for l in (ref_resources / "bqsr1-ref.observed")
        .read_text().splitlines() if l
    )
    assert ours == golden


# ---------------------------------------------------------------------------
# Markdup device reductions
# ---------------------------------------------------------------------------
CONTIGS = ["0", "1", "ref0"]
SD = SequenceDictionary(tuple(SequenceRecord(n, 10_000_000) for n in CONTIGS))
RGD = RecordGroupDictionary((RecordGroup("m", library="lib"),))


def _read(ref, start, phred=20, clipped=0, neg=False, cigar=None,
          unmapped=False):
    name = f"r{next(_counter)}"
    if unmapped:
        return dict(name=name, flags=0x4, contig_idx=-1, start=-1, mapq=0,
                    cigar="*", seq="A" * 100, qual="5" * 100,
                    read_group_idx=0)
    cigar = cigar or (f"{clipped}S{100 - clipped}M" if clipped else "100M")
    return dict(
        name=name, flags=(0x10 if neg else 0), contig_idx=SD.index(ref),
        start=start, mapq=60, cigar=cigar, seq="A" * 100,
        qual=chr(phred + 33) * 100, read_group_idx=0,
    )


@pytest.fixture(scope="module")
def md_ds():
    """Markdup-shaped inputs: clipped 5' keys, both strands, unmapped
    rows, mixed quality rows — the score/key edge cases."""
    recs = [
        _read("0", 100), _read("0", 100, phred=30),
        _read("0", 102, clipped=2), _read("1", 50, neg=True),
        _read("1", 50, neg=True, clipped=4),
        _read("ref0", 7, cigar="50M10I40M"),
        _read("ref0", 7, cigar="30M200N70M"),
        _read("0", 9, unmapped=True),
        dict(name="mixedq", flags=0, contig_idx=0, start=1, mapq=60,
             cigar="4M", seq="ACGT",
             qual=chr(33 + 20) * 2 + chr(33 + 10) * 2, read_group_idx=0),
    ]
    batch, side = pack_reads(recs)
    return AlignmentDataset(
        batch, side, SamHeader(seq_dict=SD, read_groups=RGD)
    )


def test_markdup_columns_device_match_host(md_ds):
    """The jit [N, L] reductions (5'-clipped key + phred>=15 score)
    match the numpy row_summary columns bit-for-bit."""
    from adam_tpu.ops import cigar as cigar_ops

    b = md_ds.batch.to_numpy()
    five_dev, score_dev = md.markdup_columns_device(md_ds.batch)
    five_np = cigar_ops.five_prime_position_np(
        b.start, b.end, b.flags, b.cigar_ops, b.cigar_lens, b.cigar_n
    )
    quals = np.asarray(b.quals)
    in_read = np.arange(b.lmax)[None, :] < np.asarray(b.lengths)[:, None]
    score_np = np.where(in_read & (quals >= 15), quals, 0).sum(
        axis=1, dtype=np.int32
    )
    np.testing.assert_array_equal(five_dev, five_np)
    np.testing.assert_array_equal(score_dev, score_np)


def test_mark_duplicates_device_backend_matches_host(md_ds):
    """End-to-end duplicate flags agree between the device and numpy
    backends on a batch with duplicates to mark."""
    recs = [_read("0", 42, phred=30)] + [_read("0", 42) for _ in range(5)]
    batch, side = pack_reads(recs)
    ds = AlignmentDataset(batch, side, SamHeader(seq_dict=SD, read_groups=RGD))
    f_dev = np.asarray(
        ds.mark_duplicates(backend="device").batch.to_numpy().flags
    )
    f_np = np.asarray(
        ds.mark_duplicates(backend="numpy").batch.to_numpy().flags
    )
    assert (f_dev & schema.FLAG_DUPLICATE).sum() > 0
    np.testing.assert_array_equal(f_dev, f_np)
    f_dev2 = np.asarray(
        md_ds.mark_duplicates(backend="device").batch.to_numpy().flags
    )
    f_np2 = np.asarray(
        md_ds.mark_duplicates(backend="numpy").batch.to_numpy().flags
    )
    np.testing.assert_array_equal(f_dev2, f_np2)


# ---------------------------------------------------------------------------
# Streamed pipeline under the device backend
# ---------------------------------------------------------------------------
def test_streamed_device_backend_matches_numpy(tmp_path, monkeypatch):
    """The whole streamed flagship — markdup dispatch double-buffer,
    lazy device observe fetched at the merge barrier, double-buffered
    device apply, PartWriterPool sink — is bit-identical to the numpy
    backend run."""
    from make_wgs_sam import make_wgs

    from adam_tpu.io import context
    from adam_tpu.pipelines.streamed import transform_streamed

    path = str(tmp_path / "in.sam")
    make_wgs(path, 2048, 100, n_contigs=1, contig_len=30_000)
    outs = {}
    for b in ("device", "numpy"):
        monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", b)
        out = str(tmp_path / f"{b}.adam")
        stats = transform_streamed(path, out, window_reads=512)
        assert stats["bqsr_backend"] == b
        if b == "device":
            # the device run must actually take the device code paths
            assert "md_cols_fetch_s" in stats
            assert "apply_device_dispatch_s" in stats
        outs[b] = context.load_alignments(out).compact()
    ref = outs["numpy"].batch.to_numpy()
    got = outs["device"].batch.to_numpy()
    names_ref = list(outs["numpy"].sidecar.names)
    names_got = list(outs["device"].sidecar.names)
    order_ref = np.lexsort((np.asarray(ref.flags), np.asarray(names_ref, "S64")))
    order_got = np.lexsort((np.asarray(got.flags), np.asarray(names_got, "S64")))
    assert [names_ref[i] for i in order_ref] == [names_got[i] for i in order_got]
    np.testing.assert_array_equal(
        np.asarray(ref.flags)[order_ref], np.asarray(got.flags)[order_got]
    )
    L = min(ref.lmax, got.lmax)
    np.testing.assert_array_equal(
        np.asarray(ref.quals)[order_ref][:, :L],
        np.asarray(got.quals)[order_got][:, :L],
    )
    oq_ref = [outs["numpy"].sidecar.orig_quals[i] for i in order_ref]
    oq_got = [outs["device"].sidecar.orig_quals[i] for i in order_got]
    assert oq_ref == oq_got


# ---------------------------------------------------------------------------
# Satellite regressions
# ---------------------------------------------------------------------------
def test_pos_zero_mapped_read_does_not_spill_junk_bin(tmp_path):
    """A record flagged mapped but carrying POS=0 (start == -1, as some
    aligners emit for placed-but-unaligned mates) must be dropped by the
    spill filter, not land in a junk 'bin--00001' file of the previous
    contig."""
    from adam_tpu.parallel.partitioner import GenomeBins
    from adam_tpu.parallel.sharded_join import _spill_batches

    recs = [
        _read("0", 100),
        dict(name="pos0", flags=0, contig_idx=0, start=-1, mapq=0,
             cigar="100M", seq="A" * 100, qual="I" * 100, read_group_idx=0),
    ]
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=SD, read_groups=RGD)
    bins = GenomeBins(1_000_000, SD)
    spill, n = _spill_batches(
        [(batch.to_numpy(), side, header)], bins, str(tmp_path)
    )
    try:
        touched = spill.touched_bins()
        assert all(b >= 0 for b in touched)
        # exactly the one genuinely-mapped read spilled
        assert sum(spill._counts[b] for b in touched) == 1
        assert not [
            f for f in os.listdir(str(tmp_path)) if "bin--" in f
        ]
    finally:
        spill.cleanup()


def test_part_writer_pool_roundtrip_and_error(tmp_path):
    """The double-buffered part writer writes every submitted part
    (readable back with the normal loader) and surfaces write errors at
    close()."""
    from adam_tpu.io import parquet

    recs = [_read("0", 10 + i) for i in range(6)]
    batch, side = pack_reads(recs)
    header = SamHeader(seq_dict=SD, read_groups=RGD)
    out = tmp_path / "parts"
    out.mkdir()
    pool = parquet.PartWriterPool(n_encoders=2, inflight_parts=2)
    for i in range(3):
        pool.submit(str(out / f"part-r-{i:05d}.parquet"), batch, side, header)
    pool.close()
    for i in range(3):
        back_batch, _side, _hdr = parquet.load_alignments(
            str(out / f"part-r-{i:05d}.parquet")
        )
        assert back_batch.n_rows == batch.n_rows

    bad = parquet.PartWriterPool(n_encoders=1, inflight_parts=1)
    bad.submit(
        str(tmp_path / "missing-dir" / "part.parquet"), batch, side, header
    )
    with pytest.raises(Exception):
        bad.close()
