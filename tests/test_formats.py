import numpy as np
import pytest

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch, pack_reads


def test_base_encode_decode_roundtrip():
    s = "ACGTNacgtn"
    codes = schema.encode_bases(s)
    assert list(codes) == [0, 1, 2, 3, 4, 0, 1, 2, 3, 4]
    assert schema.decode_bases(codes) == "ACGTNACGTN"


def test_complement():
    codes = schema.encode_bases("ACGTN")
    comp = schema.BASE_COMPLEMENT[codes]
    assert schema.decode_bases(comp) == "TGCAN"


def test_qual_roundtrip():
    q = "!I5"
    phred = schema.encode_quals(q)
    assert list(phred) == [0, 40, 20]
    assert schema.decode_quals(phred) == q


def test_cigar_encode_decode():
    ops, lens, n = schema.encode_cigar("10M2I5D3S", 8)
    assert n == 4
    assert list(ops[:4]) == [schema.CIGAR_M, schema.CIGAR_I, schema.CIGAR_D, schema.CIGAR_S]
    assert list(lens[:4]) == [10, 2, 5, 3]
    assert schema.decode_cigar(ops, lens, n) == "10M2I5D3S"
    assert schema.decode_cigar(*schema.encode_cigar("*", 4)[:2], 0) == "*"


def test_cigar_stats():
    qlen, rlen = schema.cigar_str_stats("10M2I5D3S")
    assert qlen == 10 + 2 + 3
    assert rlen == 10 + 5


def _recs():
    return [
        dict(name="r1", flags=0, contig_idx=0, start=100, mapq=60,
             cigar="4M", seq="ACGT", qual="IIII", attrs="", md="4"),
        dict(name="r2", flags=16, contig_idx=1, start=200, mapq=30,
             cigar="2M1I3M", seq="ACGTAC", qual="IIIIII", attrs="NM:i:1", md="5"),
        dict(name="r3", flags=4, contig_idx=-1, start=-1, mapq=255,
             cigar="*", seq="GG", qual="II", attrs="", md=None),
    ]


def test_pack_reads():
    batch, side = pack_reads(_recs())
    assert batch.n_rows == 3
    assert batch.lmax == 6
    assert batch.n_valid() == 3
    np.testing.assert_array_equal(np.asarray(batch.lengths), [4, 6, 2])
    np.testing.assert_array_equal(np.asarray(batch.start), [100, 200, -1])
    np.testing.assert_array_equal(np.asarray(batch.end), [104, 205, -1])
    assert schema.decode_bases(np.asarray(batch.bases)[1], 6) == "ACGTAC"
    assert np.asarray(batch.bases)[0, 4] == schema.BASE_PAD
    assert side.names == ["r1", "r2", "r3"]
    assert bool(np.asarray(batch.is_mapped)[2]) is False


def test_pack_rounding_and_pad_rows():
    batch, _ = pack_reads(_recs(), round_rows_to=8)
    assert batch.n_rows == 8
    assert batch.n_valid() == 3
    batch2 = batch.pad_rows(16)
    assert batch2.n_rows == 16
    assert batch2.n_valid() == 3
    assert not bool(np.asarray(batch2.valid)[10])


def test_concat_widens():
    b1, _ = pack_reads(_recs()[:1])
    b2, _ = pack_reads(_recs()[1:])
    cat = ReadBatch.concat([b1, b2])
    assert cat.n_rows == 3
    assert cat.lmax == 6
    np.testing.assert_array_equal(np.asarray(cat.lengths), [4, 6, 2])


def test_take_is_jittable():
    import jax
    batch, _ = pack_reads(_recs())
    taken = jax.jit(lambda b: b.take(np.array([2, 0])))(batch.to_device())
    np.testing.assert_array_equal(np.asarray(taken.lengths), [2, 4])


def test_fragments_to_reads_merges_adjacent(tmp_path):
    """FragmentConverter.convertRdd semantics: adjacent fragments merge
    into one synthetic read; gaps and contig changes split reads."""
    from adam_tpu.formats.fragments import FragmentBatch, to_read_records

    frags = FragmentBatch.from_sequences(
        [(0, "ACGTACGTAC"), (1, "GGGGCCCC")], fragment_length=4
    )
    # contig 0: fragments at 0,4,8 (adjacent) -> one read "ACGTACGTAC"
    # contig 1: fragments at 0,4 (adjacent) -> one read "GGGGCCCC"
    recs = to_read_records(frags, ["c0", "c1"])
    assert [(r["name"], r["start"], r["seq"]) for r in recs] == [
        ("c0", 0, "ACGTACGTAC"),
        ("c1", 0, "GGGGCCCC"),
    ]

    # introduce a gap: drop the middle fragment of contig 0
    import numpy as np

    keep = np.ones(frags.n_rows, bool)
    keep[1] = False
    gappy = frags.replace(valid=np.asarray(frags.valid) & keep)
    recs = to_read_records(gappy, ["c0", "c1"])
    assert [(r["name"], r["start"], r["seq"]) for r in recs] == [
        ("c0", 0, "ACGT"),
        ("c0", 8, "AC"),
        ("c1", 0, "GGGGCCCC"),
    ]


def test_load_alignments_fasta_and_contig_parquet(tmp_path, ref_resources):
    """The .fa and contig-parquet branches of the load dispatcher both
    yield synthetic reads (loadAlignments dispatch,
    rdd/ADAMContext.scala:484-511)."""
    from adam_tpu.io import context, fasta, parquet

    fa = ref_resources / "artificial.fa"
    ds = context.load_alignments(str(fa))
    b = ds.batch.to_numpy()
    assert int(b.valid.sum()) >= 1
    total = int(np.asarray(b.lengths)[np.asarray(b.valid)].sum())

    # write the fragments as a contig parquet store, reload via dispatcher
    frags, seq_dict, _ = fasta.read_fasta(str(fa), fragment_length=100)
    store = tmp_path / "artificial.contig.adam"
    parquet.save_fragments(str(store), frags, seq_dict)
    ds2 = context.load_alignments(str(store))
    b2 = ds2.batch.to_numpy()
    assert int(np.asarray(b2.lengths)[np.asarray(b2.valid)].sum()) == total


def test_to_fixed_bytes_native_matches_numpy():
    """The native strided gather must produce the same S-array as the
    numpy scatter path (nulls, empties, ragged widths included)."""
    import numpy as np

    from adam_tpu import native
    from adam_tpu.formats.strings import StringColumn

    if not native.available():
        import pytest

        pytest.skip("native library unavailable")
    col = StringColumn.from_list(
        ["abc", None, "", "a", "zzzzzzzz", "mid", None, "yy"]
    )
    # the native path must actually run for this parity check
    assert native.span_gather_strided(
        col.buf, col.offsets[:-1], col.lengths(), 8
    ) is not None
    fb = col.to_fixed_bytes()
    orig = native.span_gather_strided
    try:
        native.span_gather_strided = lambda *a, **k: None
        fb2 = col.to_fixed_bytes()
    finally:
        native.span_gather_strided = orig
    np.testing.assert_array_equal(fb, fb2)
    assert fb[0] == b"abc" and fb[4] == b"zzzzzzzz" and fb[2] == b""
