"""Perf ledger + regression sentinel (utils/perfledger.py,
docs/OBSERVABILITY.md "The perf ledger").

The contract under test: every run appends one durable NDJSON line of
direction-aware perf keys; the sentinel judges the newest run against
the rolling MEDIAN of its predecessors (silent until
MIN_BASELINE_RUNS of history exist), and a flagged run counts
``perf.regressions``, lands a ``perf.regression`` incident bundle,
and charges the armed SLO engine's error budget; ``adam-tpu perf``
turns the ledger into a CI gate (exit 1 on a newest-run regression).
"""

import json
import os

import pytest

from adam_tpu.utils import incidents
from adam_tpu.utils import perfledger as pl
from adam_tpu.utils import slo
from adam_tpu.utils import telemetry as tele


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    pl._reset_for_tests()
    slo._reset_for_tests()
    incidents._reset_for_tests()
    monkeypatch.setenv("ADAM_TPU_INCIDENT_COOLDOWN_S", "0")
    yield
    pl._reset_for_tests()
    slo._reset_for_tests()
    incidents._reset_for_tests()


def _snap(total_s=10.0, apply_s=2.0):
    """A minimal telemetry-snapshot shape carrying the sentinel's
    marquee keys."""
    return {
        "spans": {
            "streamed.total": {"count": 1, "total_s": total_s},
            "streamed.pass_c": {"count": 1, "total_s": apply_s + 1.0},
            "streamed.apply.dispatch": {"count": 4, "total_s": 0.5},
            "streamed.apply.fetch": {"count": 4, "total_s": 0.5},
            "streamed.write_wait": {"count": 1, "total_s": 1.0},
        },
        "counters": {"reads.ingested": 1000},
        "transfers": {
            "h2d": {"0": {"pass_c": {"bytes": 1 << 20, "n": 4},
                          "prewarm": {"bytes": 1 << 30, "n": 1}}},
            "d2h": {},
        },
        "compiles": {"entries": [
            {"kernel": "bqsr", "in_window": False},
            {"kernel": "bqsr", "in_window": True},
        ], "dropped": 0},
    }


def _seed(root, n, total_s=10.0):
    for i in range(n):
        pl.book(str(root), _snap(total_s=total_s), run_id=f"seed{i}")


# ---------------------------------------------------------------------------
# key extraction / booking / reading
# ---------------------------------------------------------------------------
def test_snapshot_keys_directions_and_identities():
    keys = pl.snapshot_keys(_snap())
    assert keys["spans.streamed.total.total_s"] == (10.0, "lower")
    assert keys["counters.reads.ingested"] == (1000.0, None)
    # pass_c - dispatch - fetch - prewarm.pass_c
    assert keys["stages.apply_split_s"] == (2.0, "lower")
    assert keys["stages.apply_split_plus_write_wait_s"] == (3.0, "lower")
    # prewarm bytes excluded from the transfer total
    assert keys["transfers.h2d.total.bytes"] == (float(1 << 20), None)
    # only the in-window cold compile counts
    assert keys["compiles.in_window"] == (1.0, "lower")


def test_book_and_read_roundtrip(tmp_path):
    entry = pl.book(str(tmp_path), _snap(), run_id="r1")
    assert entry["schema"] == pl.LEDGER_SCHEMA
    got = pl.read_ledger(str(tmp_path))
    assert len(got) == 1 and got[0]["run_id"] == "r1"
    # the ledger file itself is an accepted root spelling
    path = os.path.join(str(tmp_path), pl.LEDGER_FILENAME)
    assert pl.read_ledger(path) == got


def test_read_skips_torn_and_foreign_lines(tmp_path):
    pl.book(str(tmp_path), _snap(), run_id="good")
    path = os.path.join(str(tmp_path), pl.LEDGER_FILENAME)
    with open(path, "a") as fh:
        fh.write(json.dumps({"schema": "someone.else/9"}) + "\n")
        fh.write('{"schema": "adam_tpu.perf_ledger/1", "torn')  # no \n
    entries = pl.read_ledger(str(tmp_path))
    assert [e.get("run_id") for e in entries] == ["good"]
    assert pl.read_ledger(str(tmp_path / "missing")) == []


# ---------------------------------------------------------------------------
# baseline + compare
# ---------------------------------------------------------------------------
def test_rolling_baseline_is_median_with_quorum(tmp_path):
    _seed(tmp_path, 4, total_s=10.0)
    pl.book(str(tmp_path), _snap(total_s=100.0), run_id="outlier")
    base = pl.rolling_baseline(pl.read_ledger(str(tmp_path)), 5)
    # median absorbs the single outlier
    assert base["spans.streamed.total.total_s"][0] == pytest.approx(10.0)
    # a key present in only 1 of 5 entries misses the quorum
    pl.book(str(tmp_path), {"rare.key": (1.0, "lower")}, run_id="rare")
    base = pl.rolling_baseline(pl.read_ledger(str(tmp_path)), 5)
    assert "rare.key" not in base


def test_compare_is_direction_aware():
    baseline = {
        "a.lower": (10.0, "lower", 5),
        "b.info": (10.0, None, 5),
        "c.tiny": (1e-6, "lower", 5),
    }
    entry = {"schema": pl.LEDGER_SCHEMA, "keys": {
        "a.lower": [20.0, "lower"],   # +100% on lower-is-better: flags
        "b.info": [99.0, None],       # informational: never flags
        "c.tiny": [1.0, "lower"],     # sub-noise-floor baseline: never
    }}
    regs = pl.compare(entry, baseline, 25.0)
    assert [r["key"] for r in regs] == ["a.lower"]
    assert regs[0]["delta_pct"] == pytest.approx(100.0)
    # an improvement never flags
    faster = {"schema": pl.LEDGER_SCHEMA,
              "keys": {"a.lower": [1.0, "lower"]}}
    assert pl.compare(faster, baseline, 25.0) == []


# ---------------------------------------------------------------------------
# sentinel
# ---------------------------------------------------------------------------
def test_sentinel_silent_until_min_history(tmp_path):
    incidents.install(str(tmp_path))
    for i in range(pl.MIN_BASELINE_RUNS):
        # 10x slower every run, but history is too shallow to judge
        assert pl.sentinel(str(tmp_path), _snap(total_s=10.0 ** (i + 1)),
                           run_id=f"r{i}") == []
    assert incidents.list_bundles(str(tmp_path)) == []


def test_sentinel_flags_counts_and_fires(tmp_path):
    incidents.install(str(tmp_path))
    slo.install("t:avail>=0.99", str(tmp_path))
    was = tele.TRACE.recording
    tele.TRACE.recording = True
    try:
        _seed(tmp_path, 4)
        regs = pl.sentinel(str(tmp_path), _snap(total_s=20.0),
                           run_id="slowrun")
        assert any(r["key"] == "spans.streamed.total.total_s"
                   for r in regs)
        counters = tele.TRACE.snapshot()["counters"]
        assert counters[tele.C_PERF_REGRESSIONS] == len(regs)
        bundles = incidents.list_bundles(str(tmp_path))
        assert any(b["trigger"] == "perf.regression" for b in bundles)
        # the regression charged the SLO budget
        row = slo.status()["objectives"][0]
        assert row["bad_total"] == len(regs)
    finally:
        tele.TRACE.recording = was
        tele.TRACE.reset()


def test_sentinel_clean_run_stays_quiet(tmp_path):
    incidents.install(str(tmp_path))
    _seed(tmp_path, 4)
    assert pl.sentinel(str(tmp_path), _snap(total_s=10.1),
                       run_id="steady") == []
    assert incidents.list_bundles(str(tmp_path)) == []


def test_env_knobs_validated(monkeypatch):
    monkeypatch.setenv("ADAM_TPU_PERF_THRESHOLD", "bogus")
    assert pl.perf_threshold_pct() == pl.DEFAULT_THRESHOLD_PCT
    monkeypatch.setenv("ADAM_TPU_PERF_BASELINE_N", "7")
    assert pl.baseline_n() == 7
    monkeypatch.setenv("ADAM_TPU_PERF_LEDGER", "0")
    assert not pl.booking_enabled()
    monkeypatch.delenv("ADAM_TPU_PERF_LEDGER")
    assert pl.booking_enabled()


def test_install_seam(tmp_path):
    assert not pl.installed() and pl.ledger_root() is None
    pl.install(str(tmp_path))
    assert pl.installed()
    assert pl.ledger_root() == os.path.abspath(str(tmp_path))
    pl.uninstall()
    assert pl.ledger_root() is None


# ---------------------------------------------------------------------------
# trend + CLI
# ---------------------------------------------------------------------------
def test_trend_rows_flag_only_past_baseline_phase(tmp_path):
    _seed(tmp_path, 4)
    pl.book(str(tmp_path), _snap(total_s=20.0), run_id="slow")
    rows = pl.trend(pl.read_ledger(str(tmp_path)))
    assert [r["index"] for r in rows] == [0, 1, 2, 3, 4]
    for r in rows[:pl.MIN_BASELINE_RUNS]:
        assert r["regressions"] == []
    assert rows[-1]["total_s"] == pytest.approx(20.0)
    assert any(r["key"] == "spans.streamed.total.total_s"
               for r in rows[-1]["regressions"])


def _run_cli(argv):
    from adam_tpu.cli.main import main

    return main(argv)


def test_cli_perf_exit_codes_and_json(tmp_path, capsys):
    assert _run_cli(["perf", str(tmp_path / "empty")]) == 2
    capsys.readouterr()

    _seed(tmp_path, 4)
    pl.book(str(tmp_path), _snap(total_s=10.0), run_id="steady")
    assert _run_cli(["perf", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "regressions" in out and "steady"[-6:] in out

    pl.book(str(tmp_path), _snap(total_s=20.0), run_id="slowrun")
    assert _run_cli(["perf", str(tmp_path)]) == 1
    capsys.readouterr()

    assert _run_cli(["perf", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "adam_tpu.perf_trend/1"
    assert not doc["ok"] and doc["regressions"]

    # a generous threshold clears the same ledger
    assert _run_cli(["perf", str(tmp_path), "--threshold", "200"]) == 0
