"""Variation layer tests.

Mirrors the reference's VariantContextConverterSuite /
GenotypesSuite / ADAMVariationRDDFunctionsSuite patterns: conversion
fidelity on the shipped ``small.vcf`` fixture, multi-allelic splitting
with PL punch-out, gVCF reference-model rows, VCF round-trip, and the
allele-count / known-table derivations.
"""

import numpy as np
import pytest

from adam_tpu.api.datasets import GenotypeDataset
from adam_tpu.formats import variants as vf
from adam_tpu.io import vcf as vcf_io

SMALL_VCF = "/root/reference/adam-core/src/test/resources/small.vcf"


@pytest.fixture(scope="module")
def small(tmp_path_factory):
    return GenotypeDataset.load(SMALL_VCF)


class TestReadSmallVcf:
    def test_sites_and_samples(self, small):
        # 5 records, all bi-allelic already
        assert len(small) == 5
        assert small.callset_samples() == ["NA12878", "NA12891", "NA12892"]
        assert len(small.genotypes) == 15

    def test_coordinates_zero_based(self, small):
        v = small.variants
        # first record: 1:14397 CTGT -> C
        assert small.contig_names[v.contig_idx[0]] == "1"
        assert v.start[0] == 14396
        assert v.end[0] == 14400  # start + len(CTGT)
        assert v.sidecar.ref_allele[0] == "CTGT"
        assert v.sidecar.alt_allele[0] == "C"

    def test_filters(self, small):
        v = small.variants
        assert v.filters_applied.all()
        assert v.passing.tolist() == [False, False, True, True, True]
        assert v.sidecar.filters[0] == ["IndelQD"]

    def test_genotype_fields(self, small):
        g = small.genotypes
        # NA12878 at site 0: 0/1:16,4:20:rd:99:120,0,827
        assert g.alleles[0].tolist() == [vf.ALLELE_REF, vf.ALLELE_ALT]
        assert g.ref_depth[0] == 16 and g.alt_depth[0] == 4
        assert g.dp[0] == 20 and g.gq[0] == 99
        assert g.pl[0].tolist() == [120, 0, 827]
        assert g.genotype_filters[0] == "rd"
        # NA12892 at site 4: 1/1
        assert g.alleles[14].tolist() == [vf.ALLELE_ALT, vf.ALLELE_ALT]

    def test_variant_flags(self, small):
        v = small.variants
        assert v.is_snp.tolist() == [False, True, False, False, True]
        assert v.is_indel.tolist() == [True, False, True, True, False]

    def test_rs_ids(self, small):
        assert small.variants.sidecar.names[3] == "rs201888535"
        assert small.variants.sidecar.names[0] == ""


class TestMultiAllelicSplit:
    def write(self, tmp_path, body):
        p = tmp_path / "t.vcf"
        p.write_text(
            "##fileformat=VCFv4.1\n"
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS1\n"
            + body
        )
        return str(p)

    def test_triallelic_site_splits(self, tmp_path):
        # genotype 1/2: alt1 from allele 1, alt2 from allele 2
        path = self.write(
            tmp_path,
            "1\t100\t.\tA\tG,T\t50\tPASS\t.\tGT:AD:PL\t1/2:2,7,6:40,30,20,10,5,0\n",
        )
        ds = GenotypeDataset.load(path)
        v, g = ds.variants, ds.genotypes
        assert len(v) == 2
        assert v.sidecar.alt_allele == ["G", "T"]
        assert g.split_from_multiallelic.all()
        assert g.phased.all()  # split genotypes marked phased
        # vs G: allele 1 -> Alt, allele 2 -> OtherAlt
        assert g.alleles[0].tolist() == [vf.ALLELE_ALT, vf.ALLELE_OTHER_ALT]
        # vs T: allele 1 -> OtherAlt, allele 2 -> Alt
        assert g.alleles[1].tolist() == [vf.ALLELE_OTHER_ALT, vf.ALLELE_ALT]
        # AD punch-out keeps ref + this alt
        assert g.ref_depth.tolist() == [2, 2]
        assert g.alt_depth.tolist() == [7, 6]
        # PL punch-out: alleles {0,1} -> idx [0,1,2] = [40,30,20] -> -20
        assert g.pl[0].tolist() == [20, 10, 0]
        # alleles {0,2} -> idx [0,3,5] = [40,10,0] -> already min 0
        assert g.pl[1].tolist() == [40, 10, 0]

    def test_gvcf_reference_block(self, tmp_path):
        path = self.write(
            tmp_path, "1\t200\t.\tG\t<NON_REF>\t.\t.\tEND=300\tGT:PL\t0/0:0,30,300\n"
        )
        ds = GenotypeDataset.load(path)
        assert len(ds) == 1
        assert ds.variants.sidecar.alt_allele == [None]
        assert ds.variants.alt_len[0] == 0
        # INFO END extends the block span (1-based inclusive -> end 300)
        assert ds.variants.end[0] == 300
        g = ds.genotypes
        assert g.nonref_pl[0].tolist() == [0, 30, 300]
        assert g.pl[0].tolist() == [vf.PL_MISSING] * 3
        # round-trips: END survives in INFO, PL returns to nonref_pl
        out = str(tmp_path / "gvcf_rt.vcf")
        ds.save(out)
        back = GenotypeDataset.load(out)
        assert back.variants.end[0] == 300
        assert back.genotypes.nonref_pl[0].tolist() == [0, 30, 300]

    def test_missing_ad_entries_keep_positions(self, tmp_path):
        # '.' in AD must not shift later allele depths
        path = self.write(
            tmp_path,
            "1\t100\t.\tA\tG,T\t50\tPASS\t.\tGT:AD\t1/2:.,4,6\n",
        )
        g = GenotypeDataset.load(path).genotypes
        assert g.ref_depth.tolist() == [-1, -1]
        assert g.alt_depth.tolist() == [4, 6]

    def test_genotype_filter_round_trip(self, tmp_path):
        path = self.write(
            tmp_path, "1\t100\t.\tA\tG\t50\tPASS\t.\tGT:FT\t0/1:rd\n"
        )
        ds = GenotypeDataset.load(path)
        assert ds.genotypes.genotype_filters == ["rd"]
        out = str(tmp_path / "ft_rt.vcf")
        ds.save(out)
        assert GenotypeDataset.load(out).genotypes.genotype_filters == ["rd"]

    def test_alt_plus_nonref(self, tmp_path):
        # gVCF variant row: one real alt + <NON_REF> stays one site
        path = self.write(
            tmp_path, "1\t300\t.\tC\tT,<NON_REF>\t90\tPASS\t.\tGT:PL\t0/1:45,0,60,99,99,99\n"
        )
        ds = GenotypeDataset.load(path)
        assert len(ds) == 1
        assert ds.variants.sidecar.alt_allele == ["T"]
        assert ds.genotypes.pl[0].tolist() == [45, 0, 60]


class TestRoundTrip:
    def test_small_vcf_round_trip(self, small, tmp_path):
        out = str(tmp_path / "out.vcf")
        small.save(out)
        back = GenotypeDataset.load(out)
        v0, v1 = small.variants, back.variants
        assert np.array_equal(v0.start, v1.start)
        assert v0.sidecar.ref_allele == v1.sidecar.ref_allele
        assert v0.sidecar.alt_allele == v1.sidecar.alt_allele
        assert v0.sidecar.names == v1.sidecar.names
        assert np.array_equal(v0.passing, v1.passing)
        g0, g1 = small.genotypes, back.genotypes
        assert np.array_equal(g0.alleles, g1.alleles)
        assert np.array_equal(g0.pl, g1.pl)
        assert np.array_equal(g0.dp, g1.dp)
        assert np.array_equal(g0.ref_depth, g1.ref_depth)
        assert g0.samples == g1.samples

    def test_sort_on_save(self, tmp_path):
        p = tmp_path / "u.vcf"
        p.write_text(
            "##fileformat=VCFv4.1\n"
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
            "1\t500\t.\tA\tG\t10\tPASS\t.\n"
            "1\t100\t.\tC\tT\t10\tPASS\t.\n"
        )
        ds = GenotypeDataset.load(str(p))
        out = str(tmp_path / "sorted.vcf")
        ds.save(out, sort_on_save=True)
        starts = GenotypeDataset.load(out).variants.start
        assert starts.tolist() == sorted(starts.tolist())


class TestAnalyses:
    def test_allele_count(self, small):
        counts = small.allele_count()
        # site 752720 (0-based): all three samples 1/1 -> 6 x G
        assert ("1", 752720, "G", 6) in counts
        # site 14396: two 0/1 + one 0/0 -> 4 ref CTGT, 2 alt C
        assert ("1", 14396, "CTGT", 4) in counts
        assert ("1", 14396, "C", 2) in counts

    def test_snp_table_skips_reference_blocks(self, tmp_path):
        p = tmp_path / "g.vcf"
        p.write_text(
            "##fileformat=VCFv4.1\n"
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS\n"
            "1\t100\t.\tA\tG\t50\tPASS\t.\tGT\t0/1\n"
            "1\t200\t.\tG\t<NON_REF>\t.\t.\tEND=1000\tGT\t0/0\n"
        )
        t = GenotypeDataset.load(str(p)).snp_table()
        assert len(t) == 1  # only the real variant masks
        assert t.contains("1", 99)
        assert not t.contains("1", 500)

    def test_snp_table(self, small):
        t = small.snp_table()
        assert t.contains("1", 14521)  # SNP G->A at 0-based 14521
        assert t.contains("1", 14396)  # indel ref span masks too
        assert t.contains("1", 14399)
        assert not t.contains("1", 14400)

    def test_indel_table(self, small):
        t = small.indel_table()
        from adam_tpu.models.positions import ReferenceRegion

        recs = t.get_indels_in_region(ReferenceRegion("1", 14390, 14410))
        assert len(recs) == 1
        assert recs[0].consensus == ""  # deletion CTGT->C
        assert recs[0].region.start == 14397
        assert recs[0].region.end == 14400

    def test_join_annotations(self, small):
        keys = small.variant_keys()
        ann = small.join_annotations([keys[1], keys[3]], ["x", "y"])
        assert ann == [None, "x", None, "y", None]

    def test_genotype_stats(self):
        assert vf.rms_doubles([3.0, 4.0]) == pytest.approx(
            np.sqrt(12.5)
        )
        assert vf.rms_phred([]) == 0
        assert vf.rms_phred([30, 30]) == 30
        # callers pass per-genotype miss probabilities (1 - Pg);
        # result is 1 - prod(values)
        assert vf.variant_quality_from_genotypes(
            [0.1, 0.1]
        ) == pytest.approx(0.99)
