"""Indel realignment tests mirroring the reference's RealignIndelsSuite,
including the GATK IndelRealigner golden-file comparison on
artificial.sam -> artificial.realigned.sam."""

import numpy as np
import pytest

from adam_tpu.formats import schema
from adam_tpu.io import load_alignments
from adam_tpu.ops.mdtag import MdTag, parse_cigar
from adam_tpu.pipelines import realign as ra


def test_mismatch_quality_scoring():
    assert ra._sum_mismatch_quality("AAAAAAAA", "AAGGGGAA", [40] * 8) == 160
    assert ra._sum_mismatch_quality("AAAAAAAA", "AAAAAAAA", [40] * 8) == 0


def test_left_align_indel():
    # GG insert after AAA repeat region: AAAGG|GAA with insert normalizes left
    # 3M2I3M on seq AAAGGGAA vs ref AAAGAA (insert GG at pos 3)
    cigar = parse_cigar("4M2I2M")
    md = MdTag.parse("6", 0)
    new = ra.left_align_indel("AAGGGGAA", cigar, md)
    # preceding 'AAGG', variant 'GG' -> shift 2 left
    assert ra.cigar_to_string(new) == "2M2I4M"


def test_positions_to_shift():
    assert ra.positions_to_shift("GG", "AAGG") == 2
    assert ra.positions_to_shift("AG", "AAGG") == 1
    assert ra.positions_to_shift("TT", "AAGG") == 0


def test_generate_alternate_consensus():
    c = ra.generate_alternate_consensus("AAAGGAAA", 100, 0, parse_cigar("3M2I3M"))
    assert c.consensus == "GG" and (c.index_start, c.index_end) == (103, 104)
    c = ra.generate_alternate_consensus("AAAAAA", 100, 0, parse_cigar("3M2D3M"))
    assert c.consensus == "" and (c.index_start, c.index_end) == (103, 106)
    assert ra.generate_alternate_consensus("AAAA", 100, 0, parse_cigar("1M1I1M1D1M")) is None
    assert ra.generate_alternate_consensus("AAAA", 100, 0, parse_cigar("4M")) is None


def test_consensus_insert_into_reference():
    cons = ra.Consensus("GG", 0, 103, 104)
    assert cons.insert_into_reference("AAAATTTT", 100, 108) == "AAAGGATTTT"
    # deletion of 2bp at 103 (region [103,106) spans len+1): splices out 2 bases
    dele = ra.Consensus("", 0, 103, 106)
    assert dele.insert_into_reference("AAAATTTT", 100, 108) == "AAATTT"


def test_artificial_targets(ref_resources):
    ds = load_alignments(str(ref_resources / "artificial.sam"))
    targets = ra.find_targets(ds)
    assert len(targets) == 1
    t = targets[0]
    assert t.has_variation
    # all reads starting <= 25 map inside the target; later reads don't
    b = ds.batch.to_numpy()
    names = ds.seq_dict.names
    rank = {nm: i for i, nm in enumerate(sorted(names))}
    contig_rank = np.array([rank[nm] for nm in names])
    mapped = np.asarray(b.valid) & ((np.asarray(b.flags) & 4) == 0)
    tidx = ra.map_reads_to_targets(
        np.where(mapped, contig_rank[np.clip(b.contig_idx, 0, len(names) - 1)], -1),
        np.asarray(b.start), np.asarray(b.end), mapped,
        np.array([contig_rank[t.contig_idx]]),
        np.array([t.range_start]), np.array([t.range_end]),
    )
    for i in range(b.n_rows):
        if not b.valid[i]:
            continue
        if int(b.start[i]) <= 25:
            assert tidx[i] == 0
            assert t.range_start <= int(b.start[i]) and t.range_end >= int(b.end[i])
        else:
            assert tidx[i] < 0


def test_artificial_consensus(ref_resources):
    ds = load_alignments(str(ref_resources / "artificial.sam"))
    b = ds.batch.to_numpy()
    consensus = []
    for i in range(b.n_rows):
        if not b.valid[i] or ds.sidecar.md[i] is None:
            continue
        md = MdTag.parse(ds.sidecar.md[i], int(b.start[i]))
        if not md.mismatches:
            continue
        cigar = parse_cigar(
            schema.decode_cigar(b.cigar_ops[i], b.cigar_lens[i], int(b.cigar_n[i]))
        )
        seq = schema.decode_bases(b.bases[i], int(b.lengths[i]))
        c = ra.generate_alternate_consensus(seq, int(b.start[i]), 0, cigar)
        if c is not None and c not in consensus:
            consensus.append(c)
    assert len(consensus) >= 2
    assert (consensus[0].index_start, consensus[0].index_end) == (34, 45)
    assert consensus[0].consensus == ""
    assert (consensus[1].index_start, consensus[1].index_end) == (54, 65)
    assert consensus[1].consensus == ""


def test_artificial_reference_from_reads(ref_resources):
    ds = load_alignments(str(ref_resources / "artificial.sam"))
    b = ds.batch.to_numpy()
    reads = []
    for i in range(b.n_rows):
        if not b.valid[i] or int(b.start[i]) > 25:
            continue
        L = int(b.lengths[i])
        reads.append(
            ra._Read(
                row=i,
                seq=schema.decode_bases(b.bases[i], L),
                quals=[int(q) for q in b.quals[i][:L]],
                start=int(b.start[i]),
                cigar=parse_cigar(
                    schema.decode_cigar(b.cigar_ops[i], b.cigar_lens[i],
                                        int(b.cigar_n[i]))
                ),
                md=MdTag.parse(ds.sidecar.md[i], int(b.start[i])),
                mapq=int(b.mapq[i]),
            )
        )
    ref, ref_start, ref_end = ra._get_reference_from_reads(reads)
    ref_str = ("A" * 34 + "G" * 10 + "A" * 10 + "G" * 10 + "A" * 148)
    assert ref == ref_str[ref_start:ref_end]


def test_artificial_realigned_matches_gatk(ref_resources):
    """read4 of our realignment matches GATK IndelRealigner's output in
    (name, start, cigar, mapq) — the reference suite's golden assertion."""
    ds = load_alignments(str(ref_resources / "artificial.sam"))
    out = ds.realign_indels().sort_by_reference_position()
    gatk = load_alignments(
        str(ref_resources / "artificial.realigned.sam")
    ).sort_by_reference_position()
    assert len(out) == len(gatk)

    def rows(d, name):
        b = d.batch.to_numpy()
        res = []
        for i in range(b.n_rows):
            if b.valid[i] and d.sidecar.names[i] == name:
                res.append(
                    (
                        int(b.start[i]),
                        schema.decode_cigar(b.cigar_ops[i], b.cigar_lens[i],
                                            int(b.cigar_n[i])),
                        int(b.mapq[i]),
                    )
                )
        return res

    ours = rows(out, "read4")
    theirs = rows(gatk, "read4")
    assert len(ours) == len(theirs) and len(ours) > 0
    assert ours == theirs


def test_realign_no_targets_passthrough(ref_resources):
    ds = load_alignments(str(ref_resources / "reads12.sam"))
    out = ds.realign_indels()
    b0, b1 = ds.batch.to_numpy(), out.batch.to_numpy()
    np.testing.assert_array_equal(b0.start, b1.start)
    np.testing.assert_array_equal(b0.cigar_ops, b1.cigar_ops)


def test_shift_indel_declines_read_length_corruption():
    """A left shift that would eat the element before the indel and trim
    the indel itself (keeping total element length but changing the read
    span) stops at the last well-formed cigar instead of emitting one
    whose M span overruns the read (the walk the reference leaves
    unguarded: RichCigar.isWellFormed only pins the total)."""
    cigar = [(6, "S"), (95, "M"), (5, "D"), (1, "M")]
    out = ra.shift_indel(cigar, 2, 200)  # absurd shift budget
    assert ra.cigar_read_len(out) == ra.cigar_read_len(cigar) == 102
    assert ra._cigar_total_len(out) == ra._cigar_total_len(cigar)


def test_shift_indel_declines_insertion_erasure():
    """An over-budget shift on an insertion cigar would trim the I into
    M (total and read span both constant, reference span growing) —
    the reference-span pin declines that move and the insertion
    survives."""
    cigar = [(6, "S"), (5, "M"), (3, "I"), (90, "M")]
    out = ra.shift_indel(cigar, 2, 200)
    assert any(op == "I" for _, op in out), out
    assert sum(n for n, op in out if op in "MDN=X") == 95  # ref span kept
    assert ra.cigar_read_len(out) == ra.cigar_read_len(cigar)


def test_sweep_bucket_shape_covers_all_offsets():
    """Regression: lr rounding up past read_len must grow lc so every
    reference sweep offset o < cons_len - read_len is representable
    (read_len=100 -> lr=128 with cons_len=250 previously bucketed to
    lc=256, losing offsets 129..149)."""
    for read_len, cons_len in [(100, 250), (100, 101), (32, 33),
                               (65, 300), (100, 3000), (150, 151)]:
        lr, lc = ra.sweep_bucket_shape(read_len, cons_len)
        assert lr >= read_len and lc >= cons_len
        assert lc - lr + 1 >= cons_len - read_len, (read_len, cons_len)


def test_sweep_kernel_finds_tail_offset_match():
    """A perfect match planted past the old truncated offset range must
    be found (advisor repro: read_len=100, cons_len=250, match at 140)."""
    rng = np.random.default_rng(7)
    read_len, cons_len, planted = 100, 250, 140
    read = rng.integers(0, 4, read_len).astype(np.uint8)
    cons = rng.integers(0, 4, cons_len).astype(np.uint8)
    # make sure no accidental perfect match elsewhere, then plant one
    cons[planted : planted + read_len] = read
    lr, lc = ra.sweep_bucket_shape(read_len, cons_len)
    assert lc - lr + 1 > planted

    import jax.numpy as jnp

    rc = np.full((1, lr), schema.BASE_PAD, np.uint8)
    rq = np.zeros((1, lr), np.uint8)
    rc[0, :read_len] = read
    rq[0, :read_len] = 30
    ct = np.full((1, lc), schema.BASE_PAD, np.uint8)
    ct[0, :cons_len] = cons
    best_q, best_o = ra.sweep_kernel(
        jnp.asarray(rc), jnp.asarray(rq),
        jnp.asarray(np.array([read_len], np.int32)),
        jnp.asarray(ct), jnp.asarray(np.array([cons_len], np.int32)),
        lr, lc,
    )
    assert int(best_o[0]) == planted
    assert float(best_q[0]) == 0.0


def test_native_realign_matches_python_oracle(tmp_path):
    """The native-prep path (C++ realign.cpp + GEMM sweep) must be
    bit-identical to the pure-Python oracle on WGS-shaped data with
    planted indels: columns, MD strings, and OC/OP attrs all compared."""
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
    )
    from make_wgs_sam import make_wgs

    path = str(tmp_path / "in.sam")
    make_wgs(path, 4096, 100, n_contigs=2, contig_len=30_000)
    ds = load_alignments(path)
    out_n = ra._realign_indels_native(
        ds, "reads", None, ra.MAX_INDEL_SIZE, ra.MAX_CONSENSUS_NUMBER,
        ra.LOD_THRESHOLD, ra.MAX_TARGET_SIZE, None, "overlap",
    )
    if out_n is None:
        pytest.skip("native library unavailable")
    out_p = ra._realign_indels_py(ds)
    bn, bp = out_n.batch.to_numpy(), out_p.batch.to_numpy()
    for f in ("start", "end", "mapq", "cigar_n", "flags"):
        np.testing.assert_array_equal(
            np.asarray(getattr(bn, f)), np.asarray(getattr(bp, f)), err_msg=f
        )
    np.testing.assert_array_equal(
        np.asarray(bn.cigar_ops), np.asarray(bp.cigar_ops)
    )
    np.testing.assert_array_equal(
        np.asarray(bn.cigar_lens), np.asarray(bp.cigar_lens)
    )
    assert [out_n.sidecar.md[i] for i in range(len(ds))] == [
        out_p.sidecar.md[i] for i in range(len(ds))
    ]
    assert [out_n.sidecar.attrs[i] for i in range(len(ds))] == [
        out_p.sidecar.attrs[i] for i in range(len(ds))
    ]


def test_native_realign_knowns_without_table_matches_oracle(ref_resources):
    """consensus_model='knowns' with no indel table falls back to
    read-generated consensuses in BOTH paths (the Python else-branch)."""
    ds = load_alignments(str(ref_resources / "artificial.sam"))
    out_n = ra._realign_indels_native(
        ds, "knowns", None, ra.MAX_INDEL_SIZE, ra.MAX_CONSENSUS_NUMBER,
        ra.LOD_THRESHOLD, ra.MAX_TARGET_SIZE, None, "overlap",
    )
    if out_n is None:
        pytest.skip("native library unavailable")
    out_p = ra._realign_indels_py(ds, consensus_model="knowns")
    bn, bp = out_n.batch.to_numpy(), out_p.batch.to_numpy()
    np.testing.assert_array_equal(np.asarray(bn.start), np.asarray(bp.start))
    np.testing.assert_array_equal(
        np.asarray(bn.cigar_lens), np.asarray(bp.cigar_lens)
    )
    # the fallback actually realigns (not a no-op pass-through)
    assert not np.array_equal(
        np.asarray(bn.start), np.asarray(ds.batch.to_numpy().start)
    )


def test_sweep_gemm_kernel_matches_scan_kernel():
    """The GEMM sweep tier must reproduce the scan/conv kernel exactly
    (planted perfect match found; random reads bit-identical)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    lr, off, rt = 128, 512, 16
    n, read_len, cons_len = 4, 100, 300
    cons = rng.integers(0, 4, cons_len).astype(np.uint8)
    planted = 150
    rc = np.full((rt, lr), schema.BASE_PAD, np.uint8)
    rq = np.zeros((rt, lr), np.uint8)
    rl = np.zeros(rt, np.int32)
    pm = np.zeros(rt, bool)
    for i in range(n):
        r = rng.integers(0, 4, read_len).astype(np.uint8)
        if i == 0:
            r = cons[planted:planted + read_len]
        rc[i, :read_len] = r
        rq[i, :read_len] = 30
        rl[i] = read_len
        pm[i] = True
    ct = np.full((1, off + lr), schema.BASE_PAD, np.uint8)
    ct[0, :cons_len] = cons
    bq, bo = ra.sweep_gemm_kernel(
        jnp.asarray(rc), jnp.asarray(rq), jnp.asarray(rl), jnp.asarray(pm),
        jnp.asarray(ct), jnp.asarray(np.array([cons_len], np.int32)),
        off, rt, lr,
    )
    assert int(bo[0, 0]) == planted and float(bq[0, 0]) == 0.0
    # cross-check every real row against the scan kernel
    lr2, lc2 = ra.sweep_bucket_shape(read_len, cons_len)
    rc2 = np.full((n, lr2), schema.BASE_PAD, np.uint8)
    rc2[:, :read_len] = rc[:n, :read_len]
    rq2 = np.zeros((n, lr2), np.uint8)
    rq2[:, :read_len] = 30
    ct2 = np.full((n, lc2), schema.BASE_PAD, np.uint8)
    ct2[:, :cons_len] = cons
    sq, so = ra.sweep_kernel(
        jnp.asarray(rc2), jnp.asarray(rq2),
        jnp.asarray(np.full(n, read_len, np.int32)),
        jnp.asarray(ct2), jnp.asarray(np.full(n, cons_len, np.int32)),
        lr2, lc2,
    )
    np.testing.assert_array_equal(np.asarray(sq), np.asarray(bq)[0, :n])
    np.testing.assert_array_equal(np.asarray(so), np.asarray(bo)[0, :n])


def test_realign_overlap_work_runs_exactly_once(ref_resources, monkeypatch):
    """The overlap_work hook fires exactly once on every service path:
    the native sweep window, the no-target early returns, the forced
    Python fallback, and — the real double-run hazard — the native path
    running the hook at dispatch and THEN handing off to the fallback
    (it feeds BQSR histograms; a double run skews the table)."""
    from adam_tpu import native

    ds = load_alignments(str(ref_resources / "artificial.sam"))
    calls = {"n": 0}

    def hook():
        calls["n"] += 1

    out = ra.realign_indels(ds, overlap_work=hook)
    assert calls["n"] == 1
    assert out.batch.n_rows == ds.batch.n_rows

    # no targets: early return still runs the hook once
    calls["n"] = 0
    rows = np.flatnonzero(np.asarray(ds.batch.cigar_n) == 1)[:2]
    assert len(rows) > 0, "fixture lost its pure-match reads"
    ra.realign_indels(ds.take_rows(rows), overlap_work=hook)
    assert calls["n"] == 1

    # forced Python fallback path
    calls["n"] = 0
    monkeypatch.setenv("ADAM_TPU_REALIGN", "py")
    ra.realign_indels(ds, overlap_work=hook)
    monkeypatch.delenv("ADAM_TPU_REALIGN")
    assert calls["n"] == 1

    # native->fallback handoff AFTER the hook already ran: the native
    # path's MD rewrite fails late, the Python oracle serves the call,
    # and the hook must still have run exactly once
    calls["n"] = 0
    monkeypatch.setattr(native, "md_move_batch",
                        lambda *a, **k: None)
    out2 = ra.realign_indels(ds, overlap_work=hook)
    assert calls["n"] == 1
    assert out2.batch.n_rows == ds.batch.n_rows
