"""Multi-device tests on the 8-virtual-CPU mesh (the reference's
local[N] Spark analog)."""

import os
import pathlib

import jax
import numpy as np
import pytest

from adam_tpu.formats import schema
from adam_tpu.io import load_alignments
from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord
from adam_tpu.parallel import dist, mesh as mesh_mod, partitioner


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should force 8 CPU devices"
    return mesh_mod.genome_mesh()


def test_position_partitioner():
    sd = SequenceDictionary(
        (SequenceRecord("1", 1000), SequenceRecord("2", 1000))
    )
    part = partitioner.position_partition(
        sd, np.array([0, 0, 1, 1, -1]), np.array([0, 999, 0, 999, -1]), 4
    )
    np.testing.assert_array_equal(part, [0, 1, 2, 3, 4])
    shards = partitioner.shard_rows_by_position(
        sd, np.array([0, 1, -1]), np.array([10, 10, -1]), 2
    )
    assert [list(s) for s in shards] == [[0], [1, 2]]


def test_region_partitioner():
    sd = SequenceDictionary(
        (SequenceRecord("1", 250), SequenceRecord("2", 100))
    )
    bins = partitioner.region_partition(
        sd, np.array([0, 0, 1, -1]), np.array([0, 240, 50, -1]), 100
    )
    np.testing.assert_array_equal(bins, [0, 2, 3, -1])


def test_distributed_flagstat_matches_local(ref_resources, mesh):
    ds = load_alignments(str(ref_resources / "reads12.sam"))
    failed_d, passed_d = dist.distributed_flagstat(ds.batch, mesh)
    failed_l, passed_l = ds.flagstat()
    assert passed_d == passed_l
    assert failed_d == failed_l


def test_distributed_kmers_match_local(ref_resources, mesh):
    ds = load_alignments(str(ref_resources / "small.sam"))
    local = ds.count_kmers(11)
    distributed = dist.distributed_count_kmers(ds.batch, 11, mesh)
    assert distributed == local


def test_distributed_markdup_matches_local(ref_resources, mesh):
    """Mesh-sharded markdup (device 5' keys + scores, driver cascade)
    marks bitwise what the single-chip path marks."""
    ds = load_alignments(str(ref_resources / "reads12.sam"))
    local = ds.mark_duplicates()
    distributed = dist.distributed_markdup(ds, mesh)
    np.testing.assert_array_equal(
        np.asarray(local.batch.flags), np.asarray(distributed.batch.flags)
    )


def test_distributed_sort_rows(mesh):
    """sortByKey with payloads: rows (not just keys) cross the mesh and
    come back globally key-ordered, nothing lost."""
    rng = np.random.default_rng(3)
    n = 8 * 64
    keys = rng.integers(0, 2**40, n).astype(np.int64)
    payload = {
        "a": np.arange(n, dtype=np.int32),
        "m": rng.integers(0, 255, (n, 5)).astype(np.uint8),
    }
    import jax.numpy as jnp

    k, rows, valid = dist.distributed_sort_rows(
        jnp.asarray(keys), jax.tree.map(jnp.asarray, payload), mesh
    )
    k = np.asarray(k).ravel()
    vmask = valid.ravel()
    real_keys = k[vmask]
    assert len(real_keys) == n and (np.diff(real_keys) >= 0).all()
    a = np.asarray(rows["a"]).reshape(-1)[vmask]
    m = np.asarray(rows["m"]).reshape(-1, 5)[vmask]
    # every row arrived exactly once, attached to its own key
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(np.sort(a), np.arange(n))
    np.testing.assert_array_equal(keys[a], real_keys)
    np.testing.assert_array_equal(m, payload["m"][a])


def test_distributed_observe_matches_local(ref_resources, mesh):
    from adam_tpu.pipelines import bqsr

    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    obs_local = bqsr.build_observation_table(ds)

    # rebuild the same masks, then aggregate across the mesh
    import adam_tpu.ops.cigar as cigar_ops
    import jax.numpy as jnp
    from adam_tpu.ops.mdtag import batch_md_arrays

    b = ds.batch.to_numpy()
    is_mm, _, has_md = batch_md_arrays(ds.batch, ds.sidecar)
    flags = np.asarray(b.flags)
    read_ok = (
        np.asarray(b.valid)
        & ((flags & schema.FLAG_UNMAPPED) == 0)
        & ((flags & 0x900) == 0)
        & ((flags & schema.FLAG_DUPLICATE) == 0)
        & ((flags & schema.FLAG_FAILED_QC) == 0)
        & np.asarray(b.has_qual)
        & (np.asarray(b.mapq) > 0)
        & (np.asarray(b.mapq) != 255)
        & has_md
    )
    ref_pos = np.asarray(
        cigar_ops.reference_positions(
            jnp.asarray(b.cigar_ops), jnp.asarray(b.cigar_lens),
            jnp.asarray(b.cigar_n), jnp.asarray(b.start), b.lmax,
        )
    )
    quals = np.asarray(b.quals)
    residue_ok = (
        (quals > 0) & (quals < schema.QUAL_PAD) & (np.asarray(b.bases) < 4)
        & (ref_pos >= 0)
    )
    n_rg = len(ds.read_groups) + 1
    total_d, mism_d = jax.tree.map(
        np.asarray,
        dist.distributed_observe(ds.batch, residue_ok, is_mm, read_ok, n_rg, mesh),
    )
    # the local table is lane-grid-aligned (cycle axis centered at
    # obs_local.lmax >= b.lmax); compare the overlapping cycle window
    gl, lm = obs_local.lmax, b.lmax
    sl = np.s_[:, :, gl - lm : gl + lm + 1, :]
    np.testing.assert_array_equal(total_d, obs_local.total[sl])
    np.testing.assert_array_equal(mism_d, obs_local.mismatches[sl])
    assert obs_local.total.sum() == total_d.sum()


def test_distributed_sort(mesh):
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 2**40, size=8 * 64, dtype=np.int64)
    out = np.asarray(dist.distributed_sort_keys(keys, mesh)).ravel()
    got = out[out != np.iinfo(np.int64).max]
    np.testing.assert_array_equal(got, np.sort(keys))


def test_halo_exchange(mesh):
    chunks = np.arange(8 * 16, dtype=np.uint8).reshape(8, 16) % 250
    out = np.asarray(dist.halo_exchange_right(chunks, mesh, 4))
    assert out.shape == (8, 20)
    np.testing.assert_array_equal(out[:, :16], chunks)
    for s in range(7):
        np.testing.assert_array_equal(out[s, 16:], chunks[s + 1, :4])
    assert (out[7, 16:] == schema.BASE_PAD).all()


def test_two_process_distributed():
    """Real multi-process jax.distributed: two OS processes, one CPU
    device each, genome mesh spanning both — the collectives cross a
    process boundary over gRPC (SURVEY §2.6's DCN requirement)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coord = f"localhost:{port}"
    harness = str(pathlib.Path(__file__).parent / "multihost_harness.py")
    env = {k: v for k, v in os.environ.items()}
    procs = [
        subprocess.Popen(
            [sys.executable, harness, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert "HARNESS OK" in out, f"proc {pid} output:\n{out[-3000:]}"


def test_partition_by_contig():
    """ReferencePartitioner semantics: same contig -> same partition,
    unplaced rows -> the dedicated last partition."""
    ci = np.array([0, 1, 0, 2, -1, 1])
    part = partitioner.partition_by_contig(ci, 3)
    assert part[0] == part[2]
    assert part[1] == part[5]
    assert part[4] == 2
    shards = partitioner.shard_rows_by_contig(ci, 3)
    assert sorted(np.concatenate(shards).tolist()) == list(range(6))


def test_partition_by_contig_sparse_ids():
    """Sparse/high contig ids must not collide while partitions sit empty:
    ranks, not raw ids, feed the modulo (default = one partition per
    contig present)."""
    ci = np.array([7, 7, 40, 40, 1000, -1])
    part = partitioner.partition_by_contig(ci)
    mapped = part[[0, 2, 4]]
    assert len(set(mapped.tolist())) == 3  # distinct contigs, distinct parts
    assert part[5] == part.max()  # unplaced -> dedicated last partition


def test_host_shuffle_bam_to_shards(tmp_path):
    """Out-of-core genome shuffle: windowed BAM -> per-bin Parquet shards
    with no whole-dataset residency (SURVEY §2.6's host-level exchange
    for data exceeding HBM)."""
    import sys

    from adam_tpu import native

    if not native.available():
        pytest.skip("native codec unavailable")
    sys.path.insert(0, "/root/repo/tools")
    from make_synth_sam import make_sam

    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.parallel import host_shuffle

    sam_p = tmp_path / "s.sam"
    make_sam(str(sam_p), 6000, 100)
    ds = AlignmentDataset.load(str(sam_p))
    bam_p = tmp_path / "s.bam"
    ds.save(str(bam_p))

    paths = host_shuffle.shuffle_bam_to_shards(
        str(bam_p), 4, str(tmp_path / "shards"), batch_reads=1000
    )
    assert len(paths) >= 4
    total = 0
    prev_max = -1
    for batch, side, header in host_shuffle.iter_shards(paths):
        b = batch.to_numpy()
        v = np.asarray(b.valid)
        total += int(v.sum())
        starts = np.asarray(b.start)[v & (np.asarray(b.contig_idx) >= 0)]
        if len(starts):
            # genome-bin shards are globally range-ordered
            assert starts.min() > prev_max - 60_000_000 // 4
            prev_max = max(prev_max, int(starts.max()))
    assert total == 6000


@pytest.mark.parametrize("n_procs,n_shards", [(2, 4), (8, 16)])
def test_composed_transform_n_processes(tmp_path, n_procs, n_shards):
    """The COMPOSED flagship transform across real OS processes over a
    shared raw shard store — summaries/candidates exchange via spill
    files, observation tables merge with a cross-process device psum —
    must equal the monolithic single-process transform bit-for-bit on
    the output keys (SURVEY §2.6: the reference's whole execution model
    is this exchange, via Spark; the reference's local[N] suites test
    real shuffle paths at arbitrary N the same way,
    ADAMFunSuite.scala:22-29).  n=8 exercises the shard-store/psum
    design at a process count where contention and per-process RSS
    behave differently than at 2; each process's peak RSS must stay
    under a fixed budget."""
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))
    from make_wgs_sam import make_wgs

    from tests.multihost_harness import run_composition

    from adam_tpu.io import context
    from adam_tpu.io.sam import iter_sam_batches
    from adam_tpu.parallel import host_shuffle

    sam = str(tmp_path / "in.sam")
    make_wgs(sam, 3000, 100, n_contigs=2, contig_len=30_000)

    shard_dir = str(tmp_path / "shards")
    host_shuffle.shuffle_alignments_to_shards(
        iter_sam_batches(sam, batch_reads=1024), n_shards, shard_dir,
        fmt="raw",
    )

    # monolithic expectation
    mono = (
        context.load_alignments(sam)
        .mark_duplicates()
        .realign_indels()
        .recalibrate_base_qualities()
    )

    out_dir = str(tmp_path / "out.adam")
    results = run_composition(n_procs, shard_dir, out_dir)
    for pid, (_out, rss_gb) in enumerate(results):
        # budget: jax runtime + one shard's columns; the whole point of
        # the shard store is that per-process memory does not scale with
        # the dataset or the process count
        assert rss_gb < 1.5, (
            f"proc {pid} peak RSS {rss_gb} GB over budget"
        )

    got = context.load_alignments(out_dir)

    def keyed(d):
        b = d.batch.to_numpy()
        rows = []
        for i in range(b.n_rows):
            if not b.valid[i]:
                continue
            nc = int(b.cigar_n[i])
            rows.append((
                d.sidecar.names[i],
                int(b.flags[i]),
                int(b.start[i]),
                tuple(b.cigar_lens[i, :nc].tolist()),
                tuple(b.cigar_ops[i, :nc].tolist()),
                b.bases[i, : int(b.lengths[i])].tobytes(),
                int(b.quals[i, : int(b.lengths[i])].sum()),
                d.sidecar.md[i],
            ))
        return sorted(rows)

    assert len(got) == len(mono)
    assert keyed(got) == keyed(mono)


def test_composed_mesh_transform_capacity_retry(mesh, monkeypatch, tmp_path):
    """Drive the capacity-bounded all_to_all through the COMPOSED mesh
    transform (sort-rows -> markdup -> k-mers over one dataset) at a
    skew/size that forces the exact-capacity second exchange round
    inside the public APIs — not just the toy jit probes — and pin the
    results against the monolithic path (VERDICT r4 weak #5)."""
    import jax.numpy as jnp

    from adam_tpu.io import context
    from adam_tpu.parallel import dist

    n_dev = mesh.devices.size
    n, L = 512 * n_dev, 32
    # every read: same position, same poly-A sequence — one giant
    # duplicate pileup whose sort keys AND k-mer keys all route to a
    # single destination shard (maximal skew; the slack capacity is
    # 4/n_dev of the per-shard rows, so this must overflow)
    sam = tmp_path / "skew.sam"
    with open(sam, "w") as fh:
        fh.write("@HD\tVN:1.5\n@SQ\tSN:chr1\tLN:100000\n")
        for i in range(n):
            fh.write(
                f"r{i}\t0\tchr1\t501\t60\t{L}M\t*\t0\t0\t"
                f"{'A' * L}\t{'I' * L}\tMD:Z:{L}\n"
            )
    ds = context.load_alignments(str(sam))

    calls = {"sort": 0, "kmers": 0}
    orig_sort = dist._distributed_sort_rows_jit
    orig_kmers = dist._distributed_kmers_jit

    def sort_spy(*a, **k):
        calls["sort"] += 1
        return orig_sort(*a, **k)

    def kmers_spy(*a, **k):
        calls["kmers"] += 1
        return orig_kmers(*a, **k)

    monkeypatch.setattr(dist, "_distributed_sort_rows_jit", sort_spy)
    monkeypatch.setattr(dist, "_distributed_kmers_jit", kmers_spy)

    # composed: mesh sort (rows move), markdup over the sorted dataset,
    # k-mer exchange over the same batch
    b = ds.batch.to_numpy()
    keys = jnp.asarray(
        (np.asarray(b.contig_idx, np.int64) << 40)
        | np.asarray(b.start, np.int64)
    )
    k_out, rows, valid = dist.distributed_sort_rows(
        keys, {"row": jnp.arange(ds.batch.n_rows, dtype=jnp.int32)}, mesh
    )
    assert calls["sort"] == 2, (
        "maximal key skew must overflow the slack round and trigger "
        "the exact-capacity retry inside distributed_sort_rows"
    )
    order = np.asarray(rows["row"]).reshape(-1)[valid.ravel()]
    assert len(order) == n
    sorted_ds = ds.take_rows(order)

    md = dist.distributed_markdup(sorted_ds, mesh)
    mono = sorted_ds.mark_duplicates()
    np.testing.assert_array_equal(
        np.asarray(md.batch.flags), np.asarray(mono.batch.flags)
    )
    # the pileup marks all but one primary as duplicates
    n_dup = ((np.asarray(md.batch.flags) & schema.FLAG_DUPLICATE) != 0).sum()
    assert n_dup == n - 1

    counts = dist.distributed_count_kmers(md.batch, 21, mesh=mesh)
    assert calls["kmers"] == 2, (
        "identical k-mer keys must overflow and retry inside "
        "distributed_count_kmers"
    )
    assert counts == {"A" * 21: n * (L - 21 + 1)}


def test_capacity_bound_overflow_and_skew_split(mesh):
    """Stress the capacity-bounded all_to_all at realistic shapes with
    pathological skew: >=256 rows/device of IDENTICAL k-mers routes
    every key to one shard, overflowing the slack capacity — the
    dropped counter must fire and the exact-capacity retry must still
    produce exact counts.  Ditto the row-carrying distributed sort, and
    the empty-target -1 - start/3000 skew split must actually spread."""
    import jax.numpy as jnp

    from adam_tpu.formats.batch import ReadBatch, pack_reads
    from adam_tpu.parallel.dist import (
        _distributed_kmers_jit,
        distributed_count_kmers,
        pad_batch_for_mesh,
    )

    n_dev = mesh.devices.size
    n, L, k = 256 * n_dev, 32, 21
    # every read is poly-A: every k-mer is the SAME key, so every source
    # shard routes its entire send to one destination — the per-(source,
    # dest) capacity bound must overflow
    seq = "A" * L
    recs = [
        dict(name=f"r{i}", flags=0, contig_idx=0, start=i, mapq=60,
             cigar=f"{L}M", seq=seq, qual="I" * L, md=str(L))
        for i in range(n)
    ]
    batch, _side = pack_reads(recs)

    padded = pad_batch_for_mesh(batch, n_dev).to_device()
    m = (padded.n_rows // n_dev) * (padded.lmax - k + 1)
    cap = min(m, 4 * m // n_dev + 64)
    _s, _c, _h, dropped = _distributed_kmers_jit(
        padded.bases, padded.lengths, padded.valid, k, mesh, cap
    )
    assert int(dropped) > 0, (
        "skewed keys must overflow the slack capacity (the bound "
        "never binding means the stress is not a stress)"
    )
    # the public API retries at exact capacity: counts must be exact
    counts = distributed_count_kmers(batch, k, mesh=mesh)
    total = sum(counts.values())
    assert total == n * (L - k + 1)
    assert max(counts.values()) >= n  # the skewed keys all counted

    # row-carrying distributed sort under the same skew (all-equal keys)
    from adam_tpu.parallel.dist import distributed_sort_keys

    keys = jnp.zeros(n, jnp.int64)  # maximal skew: one destination
    out = np.asarray(distributed_sort_keys(keys, mesh)).ravel()
    real = out[out != np.iinfo(np.int64).max]
    assert len(real) == n and (real == 0).all()

    # empty-target skew split: unmatched reads spread over -1 - start/3000
    from adam_tpu.pipelines import realign as ra

    starts = np.arange(n, dtype=np.int64) * 500
    tidx = ra.map_reads_to_targets_overlap(
        np.zeros(n, np.int64), starts, starts + L,
        np.ones(n, bool),
        np.zeros(0, np.int64), np.zeros(0, np.int64), np.zeros(0, np.int64),
    )
    assert (tidx < 0).all()
    n_bins = len(np.unique(tidx))
    assert n_bins == len(np.unique(-1 - starts // 3000))
    assert n_bins >= n * 500 // 3000  # genuinely spread, not one bin
