"""Multi-device tests on the 8-virtual-CPU mesh (the reference's
local[N] Spark analog)."""

import os
import pathlib

import jax
import numpy as np
import pytest

from adam_tpu.formats import schema
from adam_tpu.io import load_alignments
from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord
from adam_tpu.parallel import dist, mesh as mesh_mod, partitioner


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest should force 8 CPU devices"
    return mesh_mod.genome_mesh()


def test_position_partitioner():
    sd = SequenceDictionary(
        (SequenceRecord("1", 1000), SequenceRecord("2", 1000))
    )
    part = partitioner.position_partition(
        sd, np.array([0, 0, 1, 1, -1]), np.array([0, 999, 0, 999, -1]), 4
    )
    np.testing.assert_array_equal(part, [0, 1, 2, 3, 4])
    shards = partitioner.shard_rows_by_position(
        sd, np.array([0, 1, -1]), np.array([10, 10, -1]), 2
    )
    assert [list(s) for s in shards] == [[0], [1, 2]]


def test_region_partitioner():
    sd = SequenceDictionary(
        (SequenceRecord("1", 250), SequenceRecord("2", 100))
    )
    bins = partitioner.region_partition(
        sd, np.array([0, 0, 1, -1]), np.array([0, 240, 50, -1]), 100
    )
    np.testing.assert_array_equal(bins, [0, 2, 3, -1])


def test_distributed_flagstat_matches_local(ref_resources, mesh):
    ds = load_alignments(str(ref_resources / "reads12.sam"))
    failed_d, passed_d = dist.distributed_flagstat(ds.batch, mesh)
    failed_l, passed_l = ds.flagstat()
    assert passed_d == passed_l
    assert failed_d == failed_l


def test_distributed_kmers_match_local(ref_resources, mesh):
    ds = load_alignments(str(ref_resources / "small.sam"))
    local = ds.count_kmers(11)
    distributed = dist.distributed_count_kmers(ds.batch, 11, mesh)
    assert distributed == local


def test_distributed_markdup_matches_local(ref_resources, mesh):
    """Mesh-sharded markdup (device 5' keys + scores, driver cascade)
    marks bitwise what the single-chip path marks."""
    ds = load_alignments(str(ref_resources / "reads12.sam"))
    local = ds.mark_duplicates()
    distributed = dist.distributed_markdup(ds, mesh)
    np.testing.assert_array_equal(
        np.asarray(local.batch.flags), np.asarray(distributed.batch.flags)
    )


def test_distributed_sort_rows(mesh):
    """sortByKey with payloads: rows (not just keys) cross the mesh and
    come back globally key-ordered, nothing lost."""
    rng = np.random.default_rng(3)
    n = 8 * 64
    keys = rng.integers(0, 2**40, n).astype(np.int64)
    payload = {
        "a": np.arange(n, dtype=np.int32),
        "m": rng.integers(0, 255, (n, 5)).astype(np.uint8),
    }
    import jax.numpy as jnp

    k, rows, valid = dist.distributed_sort_rows(
        jnp.asarray(keys), jax.tree.map(jnp.asarray, payload), mesh
    )
    k = np.asarray(k).ravel()
    vmask = valid.ravel()
    real_keys = k[vmask]
    assert len(real_keys) == n and (np.diff(real_keys) >= 0).all()
    a = np.asarray(rows["a"]).reshape(-1)[vmask]
    m = np.asarray(rows["m"]).reshape(-1, 5)[vmask]
    # every row arrived exactly once, attached to its own key
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(np.sort(a), np.arange(n))
    np.testing.assert_array_equal(keys[a], real_keys)
    np.testing.assert_array_equal(m, payload["m"][a])


def test_distributed_observe_matches_local(ref_resources, mesh):
    from adam_tpu.pipelines import bqsr

    ds = load_alignments(str(ref_resources / "bqsr1.sam"))
    obs_local = bqsr.build_observation_table(ds)

    # rebuild the same masks, then aggregate across the mesh
    import adam_tpu.ops.cigar as cigar_ops
    import jax.numpy as jnp
    from adam_tpu.ops.mdtag import batch_md_arrays

    b = ds.batch.to_numpy()
    is_mm, _, has_md = batch_md_arrays(ds.batch, ds.sidecar)
    flags = np.asarray(b.flags)
    read_ok = (
        np.asarray(b.valid)
        & ((flags & schema.FLAG_UNMAPPED) == 0)
        & ((flags & 0x900) == 0)
        & ((flags & schema.FLAG_DUPLICATE) == 0)
        & ((flags & schema.FLAG_FAILED_QC) == 0)
        & np.asarray(b.has_qual)
        & (np.asarray(b.mapq) > 0)
        & (np.asarray(b.mapq) != 255)
        & has_md
    )
    ref_pos = np.asarray(
        cigar_ops.reference_positions(
            jnp.asarray(b.cigar_ops), jnp.asarray(b.cigar_lens),
            jnp.asarray(b.cigar_n), jnp.asarray(b.start), b.lmax,
        )
    )
    quals = np.asarray(b.quals)
    residue_ok = (
        (quals > 0) & (quals < schema.QUAL_PAD) & (np.asarray(b.bases) < 4)
        & (ref_pos >= 0)
    )
    n_rg = len(ds.read_groups) + 1
    total_d, mism_d = jax.tree.map(
        np.asarray,
        dist.distributed_observe(ds.batch, residue_ok, is_mm, read_ok, n_rg, mesh),
    )
    # the local table is lane-grid-aligned (cycle axis centered at
    # obs_local.lmax >= b.lmax); compare the overlapping cycle window
    gl, lm = obs_local.lmax, b.lmax
    sl = np.s_[:, :, gl - lm : gl + lm + 1, :]
    np.testing.assert_array_equal(total_d, obs_local.total[sl])
    np.testing.assert_array_equal(mism_d, obs_local.mismatches[sl])
    assert obs_local.total.sum() == total_d.sum()


def test_distributed_sort(mesh):
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 2**40, size=8 * 64, dtype=np.int64)
    out = np.asarray(dist.distributed_sort_keys(keys, mesh)).ravel()
    got = out[out != np.iinfo(np.int64).max]
    np.testing.assert_array_equal(got, np.sort(keys))


def test_halo_exchange(mesh):
    chunks = np.arange(8 * 16, dtype=np.uint8).reshape(8, 16) % 250
    out = np.asarray(dist.halo_exchange_right(chunks, mesh, 4))
    assert out.shape == (8, 20)
    np.testing.assert_array_equal(out[:, :16], chunks)
    for s in range(7):
        np.testing.assert_array_equal(out[s, 16:], chunks[s + 1, :4])
    assert (out[7, 16:] == schema.BASE_PAD).all()


def test_two_process_distributed():
    """Real multi-process jax.distributed: two OS processes, one CPU
    device each, genome mesh spanning both — the collectives cross a
    process boundary over gRPC (SURVEY §2.6's DCN requirement)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coord = f"localhost:{port}"
    harness = str(pathlib.Path(__file__).parent / "multihost_harness.py")
    env = {k: v for k, v in os.environ.items()}
    procs = [
        subprocess.Popen(
            [sys.executable, harness, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert "HARNESS OK" in out, f"proc {pid} output:\n{out[-3000:]}"


def test_partition_by_contig():
    """ReferencePartitioner semantics: same contig -> same partition,
    unplaced rows -> the dedicated last partition."""
    ci = np.array([0, 1, 0, 2, -1, 1])
    part = partitioner.partition_by_contig(ci, 3)
    assert part[0] == part[2]
    assert part[1] == part[5]
    assert part[4] == 2
    shards = partitioner.shard_rows_by_contig(ci, 3)
    assert sorted(np.concatenate(shards).tolist()) == list(range(6))


def test_partition_by_contig_sparse_ids():
    """Sparse/high contig ids must not collide while partitions sit empty:
    ranks, not raw ids, feed the modulo (default = one partition per
    contig present)."""
    ci = np.array([7, 7, 40, 40, 1000, -1])
    part = partitioner.partition_by_contig(ci)
    mapped = part[[0, 2, 4]]
    assert len(set(mapped.tolist())) == 3  # distinct contigs, distinct parts
    assert part[5] == part.max()  # unplaced -> dedicated last partition


def test_host_shuffle_bam_to_shards(tmp_path):
    """Out-of-core genome shuffle: windowed BAM -> per-bin Parquet shards
    with no whole-dataset residency (SURVEY §2.6's host-level exchange
    for data exceeding HBM)."""
    import sys

    from adam_tpu import native

    if not native.available():
        pytest.skip("native codec unavailable")
    sys.path.insert(0, "/root/repo/tools")
    from make_synth_sam import make_sam

    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.parallel import host_shuffle

    sam_p = tmp_path / "s.sam"
    make_sam(str(sam_p), 6000, 100)
    ds = AlignmentDataset.load(str(sam_p))
    bam_p = tmp_path / "s.bam"
    ds.save(str(bam_p))

    paths = host_shuffle.shuffle_bam_to_shards(
        str(bam_p), 4, str(tmp_path / "shards"), batch_reads=1000
    )
    assert len(paths) >= 4
    total = 0
    prev_max = -1
    for batch, side, header in host_shuffle.iter_shards(paths):
        b = batch.to_numpy()
        v = np.asarray(b.valid)
        total += int(v.sum())
        starts = np.asarray(b.start)[v & (np.asarray(b.contig_idx) >= 0)]
        if len(starts):
            # genome-bin shards are globally range-ordered
            assert starts.min() > prev_max - 60_000_000 // 4
            prev_max = max(prev_max, int(starts.max()))
    assert total == 6000
