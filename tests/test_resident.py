"""Device-resident windows (ISSUE 13, docs/PERF.md "Device-resident
windows"): ingest-once H2D, cross-pass buffer donation, and the bases
half of the packed tail.

The matrix this file owes the acceptance criteria:

* toggle parsing (`ADAM_TPU_RESIDENT` through the shared env_toggle);
* kernel-level bit parity — packed-mask observe vs the plain observe,
  the fused bases+quals pack2 vs the plain apply + host packs, and the
  donating jit variants vs their copying twins;
* `ResidentWindow` refcount semantics (retain/release/drop/consumed);
* end-to-end byte parity of the streamed flagship with residency
  on/off across pool, mesh and host backends;
* the ledger contract — one `ingest` h2d entry per window with
  observe/apply h2d ≈ 0, handles all released (live-bytes gauge back
  to 0: no HBM growth across windows);
* the fault matrix — eviction mid-pass-B replays byte-identically from
  the host-retained ingest copy, and a SIGKILL'd resident run resumes
  byte-identically (`--resume`).
"""

import hashlib
import os
import signal
import subprocess
import sys
import warnings

import numpy as np
import pytest

from adam_tpu.parallel import device_pool as dp
from adam_tpu.parallel import partitioner as part_mod
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


def _sha_parts(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in os.listdir(d) if f.startswith("part-")
    }


# ---------------------------------------------------------------------------
# Toggle parsing (the shared env_toggle contract)
# ---------------------------------------------------------------------------
def test_resident_toggle_parsing(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_RESIDENT", raising=False)
    assert dp.resident_windows_enabled() is True
    assert dp.resident_windows_enabled(default=False) is False
    for raw, want in (("1", True), ("on", True), ("true", True),
                      ("0", False), ("off", False), ("false", False),
                      ("auto", True)):
        monkeypatch.setenv("ADAM_TPU_RESIDENT", raw)
        assert dp.resident_windows_enabled() is want, raw
    # a typo warns and keeps the default (the tuning-var contract)
    monkeypatch.setenv("ADAM_TPU_RESIDENT", "bogus")
    assert dp.resident_windows_enabled() is True


# ---------------------------------------------------------------------------
# Kernel parity: packed masks, pack2, donation-vs-copy
# ---------------------------------------------------------------------------
def _kernel_inputs(seed=1, g=64, gl=64, n_rg=3):
    rng = np.random.default_rng(seed)
    return dict(
        g=g, gl=gl, n_rg=n_rg,
        bases=rng.integers(0, 6, (g, gl)).astype(np.uint8),
        quals=rng.integers(0, 60, (g, gl)).astype(np.uint8),
        lengths=rng.integers(1, gl, g).astype(np.int32),
        flags=rng.integers(0, 4, g).astype(np.int32),
        rg=rng.integers(-1, n_rg - 1, g).astype(np.int32),
        res_ok=rng.random((g, gl)) < 0.6,
        is_mm=rng.random((g, gl)) < 0.2,
        read_ok=rng.random(g) < 0.8,
        has_qual=rng.random(g) < 0.9,
        valid=rng.random(g) < 0.95,
    )


def test_pack_mask_bits_roundtrip():
    from adam_tpu.ops.colpack import pack_mask_bits, unpack_mask_body

    rng = np.random.default_rng(3)
    for g, gl in ((1, 8), (7, 32), (64, 96)):
        m = rng.random((g, gl)) < 0.5
        pk = pack_mask_bits(m)
        assert pk.shape == (g, -(-gl // 8))
        np.testing.assert_array_equal(
            np.asarray(unpack_mask_body(pk, gl)), m
        )


def test_observe_packed_kernel_bit_parity():
    from adam_tpu.ops.colpack import pack_mask_bits
    from adam_tpu.pipelines.bqsr import jit_variant, observe_kernel

    k = _kernel_inputs()
    ref_t, ref_m = observe_kernel(
        k["bases"], k["quals"], k["lengths"], k["flags"], k["rg"],
        k["res_ok"], k["is_mm"], k["read_ok"], k["n_rg"], k["gl"],
    )
    got_t, got_m = jit_variant("observe_packed")(
        k["bases"], k["quals"], k["lengths"], k["flags"], k["rg"],
        pack_mask_bits(k["res_ok"]), pack_mask_bits(k["is_mm"]),
        k["read_ok"], k["n_rg"], k["gl"],
    )
    np.testing.assert_array_equal(np.asarray(ref_t), np.asarray(got_t))
    np.testing.assert_array_equal(np.asarray(ref_m), np.asarray(got_m))
    assert int(np.asarray(ref_t).sum()) > 0  # a real workload


def test_apply_pack2_kernel_bit_parity():
    """The fused bases+quals pack2 emits exactly the host packs of the
    plain apply's output quals (SANGER) and the decoded bases."""
    from adam_tpu.formats import schema
    from adam_tpu.ops.colpack import pack_rows_np
    from adam_tpu.pipelines.bqsr import (
        N_DINUC, N_QUAL, apply_pack2_kernel, apply_table_kernel,
    )

    k = _kernel_inputs(seed=5)
    rng = np.random.default_rng(6)
    tbl = rng.integers(
        0, 50, (k["n_rg"], N_QUAL, 2 * k["gl"] + 1, N_DINUC)
    ).astype(np.uint8)
    args = (k["bases"], k["quals"], k["lengths"], k["flags"], k["rg"],
            k["has_qual"], k["valid"], tbl)
    new_q = np.asarray(apply_table_kernel(*args, k["gl"]))
    pq, pb = apply_pack2_kernel(*args, k["gl"], k["g"] * k["gl"])
    q_lens = np.where(k["valid"] & k["has_qual"], k["lengths"], 0)
    b_lens = np.where(k["valid"], k["lengths"], 0)
    exp_q = pack_rows_np(
        (np.minimum(new_q, 93) + schema.SANGER_OFFSET).astype(np.uint8),
        q_lens,
    )
    exp_b = pack_rows_np(schema.BASE_DECODE_LUT256[k["bases"]], b_lens)
    np.testing.assert_array_equal(np.asarray(pq)[: len(exp_q)], exp_q)
    np.testing.assert_array_equal(np.asarray(pb)[: len(exp_b)], exp_b)
    assert len(exp_q) and len(exp_b)


def test_donating_variants_bit_parity():
    """Donation-vs-copy: the donating jit twins return bitwise the
    plain variants' outputs (on CPU the donation is ignored with a
    warning — the parity contract is what must hold everywhere)."""
    from adam_tpu.ops.colpack import pack_mask_bits
    from adam_tpu.pipelines.bqsr import N_DINUC, N_QUAL, jit_variant

    import jax.numpy as jnp

    k = _kernel_inputs(seed=9)
    rng = np.random.default_rng(10)
    tbl = rng.integers(
        0, 50, (k["n_rg"], N_QUAL, 2 * k["gl"] + 1, N_DINUC)
    ).astype(np.uint8)
    apply_args = (k["bases"], k["quals"], k["lengths"], k["flags"],
                  k["rg"], k["has_qual"], k["valid"], tbl)
    obs_args = (k["bases"], k["quals"], k["lengths"], k["flags"],
                k["rg"], pack_mask_bits(k["res_ok"]),
                pack_mask_bits(k["is_mm"]), k["read_ok"])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for kind, args, extra in (
            ("apply", apply_args, (k["gl"],)),
            ("apply_pack", apply_args, (k["gl"], k["g"] * k["gl"])),
            ("apply_pack2", apply_args, (k["gl"], k["g"] * k["gl"])),
            ("observe_packed", obs_args, (k["n_rg"], k["gl"])),
        ):
            plain = jit_variant(kind, False)(*args, *extra)
            # donated args must be fresh device arrays (donating a
            # committed numpy input is the real call shape)
            placed = tuple(jnp.asarray(a) for a in args)
            donated = jit_variant(kind, True)(*placed, *extra)
            for p, d in (
                zip(plain, donated) if isinstance(plain, tuple)
                else [(plain, donated)]
            ):
                np.testing.assert_array_equal(
                    np.asarray(p), np.asarray(d)
                )


# ---------------------------------------------------------------------------
# ResidentWindow refcount semantics
# ---------------------------------------------------------------------------
def test_resident_window_refcount():
    rw = dp.ResidentWindow(
        0, None, {"bases": np.zeros(4), "quals": np.zeros(4),
                  "lengths": np.zeros(4), "flags": np.zeros(4),
                  "read_group_idx": np.zeros(4)},
        g=4, gl=1, nbytes=160,
    )
    assert rw.alive
    assert len(rw.args()) == 5
    rw.retain()
    assert rw.release() is False  # one ref still held
    assert rw.alive
    assert rw.release() is True   # last ref frees
    assert not rw.alive
    with pytest.raises(RuntimeError):
        rw.get("bases")
    assert rw.release() is False  # idempotent after free

    rw2 = dp.ResidentWindow(1, None, {"bases": np.zeros(2)}, 2, 1, 2)
    rw2.retain()
    assert rw2.drop() is True     # drop ignores the refcount
    assert not rw2.alive
    assert rw2.drop() is False

    rw3 = dp.ResidentWindow(2, None, {"bases": np.zeros(2)}, 2, 1, 2)
    rw3.mark_consumed()
    assert not rw3.alive          # consumed handles stop offering args
    assert rw3.get("bases") is not None  # but buffers exist until release


# ---------------------------------------------------------------------------
# End-to-end: byte parity + ledger contract across the matrix
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def resident_runs(tmp_path_factory):
    """Streamed runs over one input (ragged last window + realign
    tail): residency on/off for the pool and mesh partitioners, the
    numpy host backend, and an eviction-mid-pass-B leg — each with its
    telemetry snapshot."""
    from make_wgs_sam import make_wgs

    from adam_tpu.pipelines.streamed import transform_streamed

    d = tmp_path_factory.mktemp("resident")
    path = str(d / "in.sam")
    make_wgs(path, 4500, 100, n_contigs=2, contig_len=30_000,
             indel_every=700, snp_every=400)
    legs = [
        # label, partitioner, devices, resident env, extra env
        ("host", None, None, "0", {}),
        ("pool_off", "pool", 2, "0", {}),
        ("pool_on", "pool", 2, "1", {}),
        ("mesh_on", "mesh", 2, "1", {}),
        ("pool_on_1dev", "pool", 1, "1", {}),
        # a device dies mid-pass-B: its resident windows drop and the
        # replays re-ship from the host-retained ingest copy
        # after=1: arrival 2 on device 1 is window 1's pass-B observe
        # dispatch — the eviction lands mid-pass-B with the window's
        # resident arrays pinned to the dying chip
        ("pool_on_evict", "pool", 2, "1", {
            "ADAM_TPU_FAULTS":
                "device.dispatch=permanent,device=1,after=1",
            "ADAM_TPU_RETRY_BACKOFF_S": "0.001",
            "ADAM_TPU_RETRY_ATTEMPTS": "2",
        }),
    ]
    from adam_tpu.utils import faults

    runs = {}
    for label, mode, n, resident, extra in legs:
        out = str(d / f"out.{label}.adam")
        env_keys = {"ADAM_TPU_RESIDENT": resident, **extra}
        old = {k: os.environ.get(k) for k in env_keys}
        os.environ.update(env_keys)
        if mode is not None:
            os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
        # the spec env var is only read at import: arm in-process
        faults.install(extra.get("ADAM_TPU_FAULTS"))
        tele.TRACE.reset()
        tele.TRACE.recording = True
        try:
            stats = transform_streamed(
                path, out, window_reads=2048, devices=n,
                partitioner=mode,
            )
            snap = tele.TRACE.snapshot()
        finally:
            tele.TRACE.recording = False
            faults.install(None)
            os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        runs[label] = (out, stats, snap)
    return runs


def test_resident_parts_bit_identical_across_matrix(resident_runs):
    ref = _sha_parts(resident_runs["host"][0])
    assert ref
    for label in ("pool_off", "pool_on", "mesh_on", "pool_on_1dev",
                  "pool_on_evict"):
        assert _sha_parts(resident_runs[label][0]) == ref, label


def test_resident_stats_and_counters(resident_runs):
    _, stats_on, snap_on = resident_runs["pool_on"]
    _, stats_off, snap_off = resident_runs["pool_off"]
    assert stats_on["resident_windows"] > 0
    assert stats_off["resident_windows"] == 0
    c_on = snap_on["counters"]
    c_off = snap_off["counters"]
    assert c_on[tele.C_RESIDENT_WINDOWS] == stats_on["resident_windows"]
    assert c_on[tele.C_RESIDENT_BYTES] > 0
    # refcounted release-after-pass-C: every handle released, none
    # evicted, and the live-bytes gauge back at 0 — no HBM growth
    # across windows
    assert (
        c_on[tele.C_RESIDENT_RELEASED] == c_on[tele.C_RESIDENT_WINDOWS]
    )
    assert c_on.get(tele.C_RESIDENT_EVICTED, 0) == 0
    assert snap_on["gauges"][tele.G_RESIDENT_LIVE]["last"] == 0
    assert tele.C_RESIDENT_WINDOWS not in c_off
    # clean prewarm coverage on both legs (donated-signature
    # executables dedupe against the prewarm)
    for snap in (snap_on, snap_off):
        in_window = [
            e for e in (snap.get("compiles", {}).get("entries") or [])
            if e.get("in_window")
        ]
        assert (
            snap["counters"].get(tele.C_COMPILE_IN_WINDOW, 0) == 0
        ), in_window


def _h2d_by_pass(snap):
    per = {}
    for _dev, passes in (snap.get("transfers", {}).get("h2d") or {}).items():
        for p, v in passes.items():
            per[p] = per.get(p, 0) + v["bytes"]
    return per


def test_resident_ledger_ingest_only(resident_runs):
    """The tentpole's ledger contract: residency collapses the
    per-pass h2d to one ingest entry per window — the observe and
    apply buckets drop to the per-pass scraps (bit-packed masks,
    validity bools, the once-per-run table replicas)."""
    per_on = _h2d_by_pass(resident_runs["pool_on"][2])
    per_off = _h2d_by_pass(resident_runs["pool_off"][2])
    assert "ingest" in per_on and "ingest" not in per_off
    # observe h2d ≈ 0: bit-packed masks only (8x smaller than the
    # booleans, 16x smaller than the off-leg's masks+bases+quals)
    assert per_on["observe"] < 0.1 * per_off["observe"]
    # the one ingest placement is smaller than what the off leg
    # re-ships across its passes for the same arrays
    dispatch_on = per_on["observe"] + per_on.get("apply", 0)
    assert dispatch_on < per_on["ingest"]
    total_on = sum(v for k, v in per_on.items() if k != "prewarm")
    total_off = sum(v for k, v in per_off.items() if k != "prewarm")
    assert total_on < total_off / 1.5


def test_resident_eviction_drops_handles(resident_runs):
    """The eviction leg: the dead device's resident windows dropped
    (device.resident.evicted > 0) and their replays re-shipped from
    the host copy — output byte-identity is asserted in the matrix
    test above."""
    _, stats, snap = resident_runs["pool_on_evict"]
    c = snap["counters"]
    assert c.get(tele.C_DEVICE_EVICTED, 0) >= 1
    assert c.get(tele.C_RESIDENT_EVICTED, 0) > 0
    # every handle left the registry one way or the other
    assert (
        c[tele.C_RESIDENT_RELEASED] + c[tele.C_RESIDENT_EVICTED]
        == c[tele.C_RESIDENT_WINDOWS]
    )
    assert snap["gauges"][tele.G_RESIDENT_LIVE]["last"] == 0


def test_analyzer_residency_section(resident_runs):
    from adam_tpu.utils import analyzer

    rep_on = analyzer.analyze(resident_runs["pool_on"][2])
    res = rep_on["residency"]
    assert res["windows"] > 0 and res["bytes"] > 0
    assert res["ingest_only"] is True
    assert "ingest" in res["h2d_by_pass"]
    assert res["donated_compiles"]["in_window"] == 0
    text = analyzer.render_report(rep_on)
    assert "Device residency" in text and "ingest-only" in text
    # the off leg renders no residency section
    rep_off = analyzer.analyze(resident_runs["pool_off"][2])
    assert rep_off["residency"] == {}


def test_packed_columns_take_and_arrow():
    """PackedColumns row-subset + zero-copy sequence column parity
    against the host LUT path."""
    import pyarrow as pa

    from adam_tpu.formats import schema
    from adam_tpu.io.arrow_pack import (
        PackedColumns, PackedQuals, packed_base_array,
    )
    from adam_tpu.ops.colpack import pack_rows_np

    rng = np.random.default_rng(2)
    n, L = 40, 24
    bases = rng.integers(0, 6, (n, L)).astype(np.uint8)
    lengths = rng.integers(1, L, n).astype(np.int64)
    valid = rng.random(n) < 0.8
    b_lens = np.where(valid, lengths, 0)
    packed = PackedColumns(
        quals=PackedQuals(np.zeros(0, np.uint8), np.zeros(n, np.int64)),
        bases=PackedQuals(
            pack_rows_np(schema.BASE_DECODE_LUT256[bases], b_lens),
            b_lens,
        ),
    )
    rows = np.flatnonzero(valid)
    taken = packed.take(rows)
    got = packed_base_array(taken.bases)
    dec = schema.BASE_DECODE_LUT256[bases]
    want = pa.array(
        [dec[i, : lengths[i]].tobytes().decode("ascii") for i in rows],
        pa.large_string(),
    )
    assert got.cast(pa.large_string()).equals(want)


# ---------------------------------------------------------------------------
# SIGKILL mid-pass-B on the resident path, then --resume
# ---------------------------------------------------------------------------
_KILL_DRIVER = (
    "import sys\n"
    "try:\n"
    "    import jax, jax._src.xla_bridge as xb\n"
    "    xb._backend_factories.pop('axon', None)\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "except Exception: pass\n"
    "from adam_tpu.pipelines.streamed import transform_streamed\n"
    "transform_streamed(sys.argv[1], sys.argv[2], window_reads=512,\n"
    "                   devices=2,\n"
    "                   run_dir=sys.argv[3], resume=sys.argv[4] == '1')\n"
)


def test_resident_sigkill_mid_pass_b_then_resume(tmp_path):
    """SIGKILL a resident --devices 2 run at the mid-pass-B phase
    boundary (device-resident windows in flight, nothing persisted),
    then --resume: byte-identical to an uninterrupted run."""
    from make_wgs_sam import make_wgs

    from adam_tpu.pipelines.streamed import transform_streamed

    path = str(tmp_path / "in.sam")
    make_wgs(path, 2000, 100, n_contigs=2, contig_len=20_000,
             indel_every=700, snp_every=400)
    clean = str(tmp_path / "clean.adam")
    transform_streamed(path, clean, window_reads=512)
    baseline = _sha_parts(clean)
    assert baseline

    out, rd = str(tmp_path / "out.adam"), str(tmp_path / "run")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=2"),
        "ADAM_TPU_NO_COMPILE_CACHE": "1",
        "ADAM_TPU_BQSR_BACKEND": "device",
        "ADAM_TPU_RESIDENT": "1",
        "ADAM_TPU_FAULTS": "proc.kill=kill,device=pass_b,after=1,times=1",
    })
    cwd = os.path.join(os.path.dirname(__file__), "..")
    rc = subprocess.run(
        [sys.executable, "-c", _KILL_DRIVER, path, out, rd, "0"],
        env=env, cwd=cwd,
    ).returncode
    assert rc == -signal.SIGKILL, f"expected SIGKILL, got {rc}"
    env.pop("ADAM_TPU_FAULTS")
    rc = subprocess.run(
        [sys.executable, "-c", _KILL_DRIVER, path, out, rd, "1"],
        env=env, cwd=cwd,
    ).returncode
    assert rc == 0
    assert _sha_parts(out) == baseline
