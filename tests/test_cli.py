"""CLI layer tests — mirror the adam-cli suites (FlagStatTest, ViewSuite,
FlattenSuite, PluginExecutorSuite, Features2ADAMSuite) plus smoke tests
for every registered command group."""

import json

import numpy as np
import pytest

from adam_tpu.cli.main import command_groups, main


def run_cli(*argv):
    return main(list(argv))


def test_registry_matches_reference():
    """Same command names as ADAMMain.scala:30-72, plus this repo's
    observability extensions (``analyze`` — the post-hoc run report —
    and ``top`` — the live heartbeat dashboard), the contract
    tooling (``check`` — the static analyzer, docs/STATIC_ANALYSIS.md —
    and ``perf`` — the perf-ledger regression gate, utils/perfledger)
    the multi-job service front (``serve`` — adam_tpu/serve), the
    HTTP gateway's client verbs (``submit``/``status``/``fetch``/
    ``cancel`` — adam_tpu/gateway, docs/SERVING.md) and the incident
    recorder's reader (``incidents`` — utils/incidents,
    docs/OBSERVABILITY.md); none has a reference analog."""
    names = {c.name for _, cmds in command_groups() for c in cmds}
    assert names == {
        "depth", "count_kmers", "count_contig_kmers", "transform",
        "serve", "submit", "status", "fetch", "cancel",
        "adam2fastq", "plugin", "flatten",
        "bam2adam", "vcf2adam", "anno2adam", "adam2vcf", "fasta2adam",
        "features2adam", "wigfix2bed",
        "print", "print_genes", "flagstat", "print_tags", "listdict",
        "allelecount", "buildinfo", "view",
        "analyze", "top", "check", "incidents", "perf",
    }


def test_usage_banner(capsys):
    assert run_cli() == 0
    out = capsys.readouterr().out
    assert "ADAM ACTIONS" in out and "transform" in out


def test_unknown_command():
    assert run_cli("bogus") == 1


def test_transform_roundtrip(ref_resources, tmp_path):
    src = str(ref_resources / "small.sam")
    out = str(tmp_path / "small.adam")
    assert run_cli("transform", src, out) == 0
    out2 = str(tmp_path / "sorted.sam")
    assert run_cli("transform", out, out2, "-sort_reads") == 0
    from adam_tpu.io import context

    ds = context.load_alignments(out2)
    b = ds.batch.to_numpy()
    starts = np.asarray(b.start)[np.asarray(b.valid)]
    assert (np.diff(starts) >= 0).all()


def test_transform_markdup_bqsr(ref_resources, tmp_path, capsys):
    src = str(ref_resources / "bqsr1.sam")
    out = str(tmp_path / "out.adam")
    obs = str(tmp_path / "obs.csv")
    assert run_cli(
        "transform", src, out,
        "-recalibrate_base_qualities",
        "-known_snps", str(ref_resources / "bqsr1.vcf"),
        "-dump_observations", obs,
        "-print_metrics",
    ) == 0
    assert "Base Quality Recalibration" in capsys.readouterr().out
    assert open(obs).read().startswith("ReadGroup,")


def test_flagstat_command(ref_resources, capsys):
    assert run_cli("flagstat", str(ref_resources / "reads12.sam")) == 0
    out = capsys.readouterr().out
    assert "in total" in out and "200" in out


def test_count_kmers(ref_resources, tmp_path, capsys):
    out = str(tmp_path / "kmers.txt")
    assert run_cli(
        "count_kmers", str(ref_resources / "small.sam"), out, "21",
        "-printHistogram",
    ) == 0
    lines = open(out).read().splitlines()
    assert lines and all(", " in ln for ln in lines)


def test_count_contig_kmers(ref_resources, tmp_path):
    fa = ref_resources / "contigs.fa"
    if not fa.exists():
        fa = ref_resources / "artificial.fa"
    out = str(tmp_path / "kmers.txt")
    assert run_cli("count_contig_kmers", str(fa), out, "10") == 0
    assert open(out).read()


def test_view_filters(ref_resources, capsys, tmp_path):
    src = str(ref_resources / "reads12.sam")
    assert run_cli("view", src, "-c") == 0
    total = int(capsys.readouterr().out.strip())
    assert total == 200
    # -f 16: reads on reverse strand only
    assert run_cli("view", src, "-f", "16", "-c") == 0
    rev = int(capsys.readouterr().out.strip())
    assert run_cli("view", src, "-F", "16", "-c") == 0
    fwd = int(capsys.readouterr().out.strip())
    assert rev + fwd == total and 0 < rev < total
    # SAM to stdout
    assert run_cli("view", src, "-f", "16") == 0
    sam_out = capsys.readouterr().out.splitlines()
    assert len(sam_out) == rev
    # save filtered output
    out = str(tmp_path / "rev.sam")
    assert run_cli("view", src, "-f", "16", "-o", out) == 0
    from adam_tpu.io import context

    assert len(context.load_alignments(out)) == rev


def test_vcf_adam_roundtrip(ref_resources, tmp_path):
    vcf_in = str(ref_resources / "small.vcf")
    adam = str(tmp_path / "v.adam")
    vcf_out = str(tmp_path / "out.vcf")
    assert run_cli("vcf2adam", vcf_in, adam) == 0
    assert run_cli("adam2vcf", adam, vcf_out) == 0
    body = [
        ln for ln in open(vcf_out).read().splitlines()
        if not ln.startswith("#")
    ]
    orig = [
        ln for ln in open(vcf_in).read().splitlines()
        if not ln.startswith("#")
    ]
    assert len(body) >= len(orig)  # multi-allelic splits may add rows


def test_allelecount(ref_resources, tmp_path):
    out = str(tmp_path / "ac.txt")
    assert run_cli("allelecount", str(ref_resources / "small.vcf"), out) == 0
    rows = [ln.split("\t") for ln in open(out).read().splitlines()]
    assert rows and all(len(r) == 4 for r in rows)


def test_fasta2adam_and_print(ref_resources, tmp_path, capsys):
    fa = ref_resources / "contigs.fa"
    if not fa.exists():
        fa = ref_resources / "artificial.fa"
    adam = str(tmp_path / "contigs.adam")
    assert run_cli("fasta2adam", str(fa), adam, "-verbose") == 0
    capsys.readouterr()
    assert run_cli("print", adam) == 0
    lines = capsys.readouterr().out.splitlines()
    assert lines and json.loads(lines[0])["fragmentSequence"]


def test_features2adam_flatten(tmp_path):
    bed = tmp_path / "x.bed"
    bed.write_text("chr1\t10\t100\tpeak1\t5.5\t+\nchr2\t20\t40\tpeak2\t.\t-\n")
    adam = str(tmp_path / "f.adam")
    flat = str(tmp_path / "f.flat.adam")
    assert run_cli("features2adam", str(bed), adam) == 0
    assert run_cli("flatten", adam, flat) == 0
    import pyarrow.parquet as pq

    t = pq.read_table(flat)
    assert t.num_rows == 2
    assert "parentIds" in t.column_names  # JSON-stringified list column
    from adam_tpu.io import parquet as pio

    feats = pio.load_features(adam)
    assert len(feats) == 2 and feats.contig_names == ["chr1", "chr2"]


def test_wigfix2bed(tmp_path):
    wig = tmp_path / "x.wigFix"
    wig.write_text(
        "fixedStep chrom=chr1 start=100 step=1\n0.5\n0.25\n"
    )
    out = str(tmp_path / "x.bed")
    assert run_cli("wigfix2bed", str(wig), "-o", out) == 0
    rows = [ln.split("\t") for ln in open(out).read().splitlines()]
    assert rows[0][:3] == ["chr1", "99", "100"]
    assert rows[1][:3] == ["chr1", "100", "101"]


def test_adam2fastq(ref_resources, tmp_path):
    src = str(ref_resources / "interleaved_fastq_sample1.ifq")
    fq1 = str(tmp_path / "r1.fq")
    fq2 = str(tmp_path / "r2.fq")
    assert run_cli("adam2fastq", src, fq1, fq2) == 0
    n1 = len(open(fq1).read().splitlines())
    n2 = len(open(fq2).read().splitlines())
    assert n1 == n2 and n1 % 4 == 0 and n1 > 0


def test_listdict(ref_resources, capsys):
    assert run_cli("listdict", str(ref_resources / "reads12.sam")) == 0
    out = capsys.readouterr().out.splitlines()
    assert out[0].split("\t")[0] == "1"


def test_print_tags(ref_resources, capsys):
    assert run_cli("print_tags", str(ref_resources / "reads12.sam"),
                   "-list", "2") == 0
    out = capsys.readouterr().out
    assert "Total: 200" in out


def test_print_genes(ref_resources, capsys):
    gtf = ref_resources / "features/Homo_sapiens.GRCh37.75.trun20.gtf"
    # assert, don't skip: a silently-vanishing parity test is no test
    # (the fixture ships in the reference tree; its absence means the
    # environment is broken, not that parity holds)
    assert gtf.exists(), f"reference gtf fixture missing: {gtf}"
    assert run_cli("print_genes", str(gtf)) == 0
    out = capsys.readouterr().out
    assert "Gene " in out and "Transcript" in out


def test_buildinfo(capsys):
    assert run_cli("buildinfo") == 0
    assert "adam-tpu version" in capsys.readouterr().out


def test_depth(ref_resources, capsys):
    assert run_cli(
        "depth", str(ref_resources / "bqsr1.sam"),
        str(ref_resources / "bqsr1.vcf"),
    ) == 0
    out = capsys.readouterr().out.splitlines()
    assert out[0] == "location\tname\tdepth"
    assert len(out) > 1


def test_bam2adam(ref_resources, tmp_path):
    src = ref_resources / "reads12.sam"
    adam = str(tmp_path / "r.adam")
    assert run_cli("bam2adam", str(src), adam) == 0
    from adam_tpu.io import context

    assert len(context.load_alignments(adam)) == 200


# ------------------------------------------------------------- plugin

from adam_tpu import plugins as P  # noqa: E402


class TakeFivePlugin(P.AdamPlugin):
    """Test plugin: mirrors the reference's Take10Plugin
    (PluginExecutorSuite)."""

    projection = ["readName", "sequence"]

    def run(self, ds, args):
        return ds.sidecar.names[:5]


def test_plugin_execution(ref_resources, capsys):
    assert run_cli(
        "plugin", "tests.test_cli.TakeFivePlugin",
        str(ref_resources / "reads12.sam"),
    ) == 0
    out = capsys.readouterr().out.splitlines()
    assert len(out) == 5


def test_plugin_rejects_non_plugin():
    with pytest.raises(TypeError):
        P.load_plugin("tests.test_cli.run_cli")


def test_transform_checkpoint_restart(ref_resources, tmp_path, capsys):
    """Stage checkpoint-restart: a rerun resumes from the deepest
    completed stage instead of recomputing (the framework's
    failure-recovery story)."""
    import json

    from adam_tpu.cli.main import main

    inp = str(ref_resources / "bqsr1.sam")
    out1 = str(tmp_path / "o1.adam")
    ck = str(tmp_path / "ck")
    rc = main(["transform", inp, out1, "-mark_duplicate_reads",
               "-sort_reads", "-checkpoint_dir", ck])
    assert rc == 0
    manifest = json.loads((tmp_path / "ck" / "MANIFEST.json").read_text())
    assert manifest["completed"] == ["mark_duplicates", "sort"]

    # corrupt-resume semantics: drop the sort checkpoint; rerun resumes
    # from mark_duplicates and redoes only sort
    import shutil
    shutil.rmtree(tmp_path / "ck" / "sort.adam", ignore_errors=True)
    (tmp_path / "ck" / "sort.adam").unlink(missing_ok=True)
    (tmp_path / "ck" / "MANIFEST.json").write_text(
        json.dumps({"stages": ["mark_duplicates", "sort"],
                    "completed": ["mark_duplicates"]})
    )
    out2 = str(tmp_path / "o2.adam")
    rc = main(["transform", inp, out2, "-mark_duplicate_reads",
               "-sort_reads", "-checkpoint_dir", ck])
    assert rc == 0
    from adam_tpu.io import context
    d1 = context.load_alignments(out1)
    d2 = context.load_alignments(out2)
    np.testing.assert_array_equal(
        np.asarray(d1.batch.start), np.asarray(d2.batch.start)
    )
    np.testing.assert_array_equal(
        np.asarray(d1.batch.flags), np.asarray(d2.batch.flags)
    )

    # changed stage composition invalidates old checkpoints
    out3 = str(tmp_path / "o3.adam")
    rc = main(["transform", inp, out3, "-sort_reads", "-checkpoint_dir", ck])
    assert rc == 0
    manifest = json.loads((tmp_path / "ck" / "MANIFEST.json").read_text())
    assert manifest["stages"] == ["sort"]


def test_transform_shards_matches_monolithic(ref_resources, tmp_path):
    """-shards N routes through the composed sharded pipeline and its
    output matches the monolithic transform on the same stage set."""
    src = str(ref_resources / "bqsr1.sam")
    out_sh = str(tmp_path / "sharded.adam")
    out_mono = str(tmp_path / "mono.adam")
    assert run_cli(
        "transform", src, out_sh, "-shards", "3",
        "-mark_duplicate_reads", "-recalibrate_base_qualities",
    ) == 0
    assert run_cli(
        "transform", src, out_mono,
        "-mark_duplicate_reads", "-recalibrate_base_qualities",
    ) == 0
    from adam_tpu.io import context

    a = context.load_alignments(out_sh)
    b = context.load_alignments(out_mono)
    ba, bb = a.batch.to_numpy(), b.batch.to_numpy()

    # shard output is bin-ordered; compare full per-row records keyed by
    # name (start/cigar/bases/quals included so a positional or rewrite
    # divergence in the sharded path cannot hide behind a weak key)
    def keyed(ds, nb):
        rows = []
        for i, name in enumerate(ds.sidecar.names):
            nc = int(nb.cigar_n[i])
            rows.append((
                name,
                int(nb.flags[i]),
                int(nb.start[i]),
                tuple(nb.cigar_lens[i, :nc].tolist()),
                tuple(nb.cigar_ops[i, :nc].tolist()),
                nb.bases[i, : int(nb.lengths[i])].tobytes(),
                int(nb.quals[i].sum()),
            ))
        return sorted(rows)

    assert keyed(a, ba) == keyed(b, bb)


def test_transform_shards_streaming_mutually_exclusive(ref_resources, tmp_path):
    src = str(ref_resources / "bqsr1.sam")
    out = str(tmp_path / "x.adam")
    assert run_cli("transform", src, out, "-shards", "2", "-streaming") == 2
