import numpy as np

from adam_tpu.formats import schema
from adam_tpu.io import load_alignments


def test_sort_reads12(ref_resources):
    ds = load_alignments(str(ref_resources / "reads12.sam")).sort_by_reference_position()
    b = ds.batch.to_numpy()
    valid = np.asarray(b.valid)
    contigs = np.asarray(b.contig_idx)[valid]
    starts = np.asarray(b.start)[valid]
    names = ds.seq_dict.names
    # non-decreasing (contig-name-rank, start)
    ranks = np.argsort(np.argsort(np.array(names, dtype=object)))
    keys = list(zip((ranks[contigs]).tolist(), starts.tolist()))
    assert keys == sorted(keys)


def test_sort_unmapped_last_by_name():
    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io.sam import SamHeader
    from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord

    sd = SequenceDictionary((SequenceRecord("chr2", 1000), SequenceRecord("chr10", 1000)))
    recs = [
        dict(name="u_b", flags=4, contig_idx=-1, start=-1, mapq=0, cigar="*",
             seq="AC", qual="II"),
        dict(name="m1", flags=0, contig_idx=0, start=5, mapq=60, cigar="2M",
             seq="AC", qual="II"),
        dict(name="u_a", flags=4, contig_idx=-1, start=-1, mapq=0, cigar="*",
             seq="AC", qual="II"),
        dict(name="m2", flags=0, contig_idx=1, start=1, mapq=60, cigar="2M",
             seq="AC", qual="II"),
    ]
    batch, side = pack_reads(recs)
    ds = AlignmentDataset(batch, side, SamHeader(seq_dict=sd))
    out = ds.sort_by_reference_position()
    # lexicographic contig names: chr10 < chr2, unmapped last by name
    assert out.sidecar.names == ["m2", "m1", "u_a", "u_b"]


def test_sort_placed_unmapped_goes_last():
    """FLAG 0x4 with mate's RNAME/POS still sorts after mapped reads."""
    from adam_tpu.api.datasets import AlignmentDataset
    from adam_tpu.formats.batch import pack_reads
    from adam_tpu.io.sam import SamHeader
    from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord

    sd = SequenceDictionary((SequenceRecord("1", 1000),))
    recs = [
        dict(name="placed_unmapped", flags=4, contig_idx=0, start=5, mapq=0,
             cigar="*", seq="AC", qual="II"),
        dict(name="mapped_late", flags=0, contig_idx=0, start=500, mapq=60,
             cigar="2M", seq="AC", qual="II"),
    ]
    batch, side = pack_reads(recs)
    ds = AlignmentDataset(batch, side, SamHeader(seq_dict=sd))
    out = ds.sort_by_reference_position()
    assert out.sidecar.names == ["mapped_late", "placed_unmapped"]
