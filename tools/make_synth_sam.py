"""Generate a synthetic SAM file for throughput benchmarking.

Paired-end reads with realistic fields: duplicates (same 5' positions),
MD tags with mismatches, RG tags, a known-SNPs sidecar — enough structure
to drive markdup + BQSR + realign end-to-end at scale.
"""

from __future__ import annotations

import argparse

import numpy as np


def make_sam(path: str, n_reads: int, read_len: int = 100, seed: int = 0,
             contig_len: int = 60_000_000) -> None:
    rng = np.random.default_rng(seed)
    n_pairs = n_reads // 2
    bases = np.frombuffer(b"ACGT", np.uint8)

    # ~10% duplicate pairs: sample 0.9*n_pairs unique sites, reuse some
    n_sites = max(1, int(n_pairs * 0.9))
    sites = rng.integers(0, contig_len - 2000, n_sites)
    site_of_pair = np.concatenate(
        [np.arange(n_sites), rng.integers(0, n_sites, n_pairs - n_sites)]
    )
    starts1 = sites[site_of_pair]
    isize = rng.integers(200, 400, n_pairs)
    starts2 = starts1 + isize - read_len

    seqs = bases[rng.integers(0, 4, (n_pairs * 2, read_len))]
    quals = (rng.integers(20, 40, (n_pairs * 2, read_len)) + 33).astype(np.uint8)

    with open(path, "w") as fh:
        fh.write("@HD\tVN:1.5\tSO:unsorted\n")
        fh.write(f"@SQ\tSN:chr20\tLN:{contig_len}\n")
        fh.write("@RG\tID:rg1\tSM:sample\tLB:lib1\tPL:ILLUMINA\n")
        fh.write("@RG\tID:rg2\tSM:sample\tLB:lib2\tPL:ILLUMINA\n")
        lines = []
        for p in range(n_pairs):
            name = f"read{p}"
            rg = "rg1" if p % 3 else "rg2"
            s1, s2 = int(starts1[p]), int(starts2[p])
            tl = int(isize[p])
            seq1 = seqs[2 * p].tobytes().decode()
            seq2 = seqs[2 * p + 1].tobytes().decode()
            q1 = quals[2 * p].tobytes().decode()
            q2 = quals[2 * p + 1].tobytes().decode()
            # one mismatch at a deterministic offset in read1's MD
            off = (p * 37) % (read_len - 2) + 1
            md1 = f"{off}A{read_len - off - 1}"
            md2 = str(read_len)
            # ~3% of pairs carry a 2bp insertion in read1 (realignment
            # target material: IndelRealignmentTarget from CIGAR I ops)
            if p % 33 == 0:
                ins_at = (p * 13) % (read_len - 10) + 4
                cig1 = f"{ins_at}M2I{read_len - ins_at - 2}M"
                md1 = str(read_len - 2)
            else:
                cig1 = f"{read_len}M"
            lines.append(
                f"{name}\t99\tchr20\t{s1 + 1}\t60\t{cig1}\t=\t{s2 + 1}\t{tl}"
                f"\t{seq1}\t{q1}\tRG:Z:{rg}\tMD:Z:{md1}\tNM:i:1\n"
            )
            lines.append(
                f"{name}\t147\tchr20\t{s2 + 1}\t60\t{read_len}M\t=\t{s1 + 1}\t{-tl}"
                f"\t{seq2}\t{q2}\tRG:Z:{rg}\tMD:Z:{md2}\tNM:i:0\n"
            )
            if len(lines) >= 20000:
                fh.write("".join(lines))
                lines = []
        fh.write("".join(lines))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("path")
    ap.add_argument("--reads", type=int, default=1_000_000)
    ap.add_argument("--len", type=int, default=100, dest="read_len")
    args = ap.parse_args()
    make_sam(args.path, args.reads, args.read_len)
    print(f"wrote {args.path}: {args.reads} reads x {args.read_len}bp")
