"""Generate realistic WGS-shaped benchmark data from a true reference.

Unlike make_synth_sam (uniform reads, random sequences, contradictory MD
tags), reads here are *sampled from a simulated genome*, so every stage
does representative work:

* multiple contigs, ~30x coverage (dense pileups -> real duplicate
  groups and realignment targets with many reads);
* planted heterozygous indels every ~2 kb: half the reads over a site
  carry the indel (CIGAR I/D + correct MD), half don't — consensus
  generation, sweeps and LOD decisions all engage;
* planted SNPs (the dbSNP analog) written to a known-sites VCF for
  BQSR config 3, plus quality-correlated sequencing errors with exact
  MD tags — the empirical-quality signal BQSR is supposed to recover;
* read-length variation, soft-clips, unmapped pairs, two libraries;
* coordinate-sorted SAM (and optionally BAM) output.

The mirror of the reference's benchmark inputs (BASELINE.md configs 2-4:
chr20-shaped BAM, dbSNP known sites, indel-dense realignment).
"""

from __future__ import annotations

import argparse

import numpy as np

_BASES = np.frombuffer(b"ACGT", np.uint8)


def _phred_profile(rng, n, read_len):
    """Position-dependent declining quality with per-read jitter."""
    pos = np.arange(read_len)
    base = 38.0 - 12.0 * (pos / max(read_len - 1, 1)) ** 2
    jitter = rng.normal(0, 3, (n, read_len))
    q = np.clip(base[None, :] + jitter, 2, 40).astype(np.uint8)
    return q


def _md_for(ref_slice: np.ndarray, read: np.ndarray) -> str:
    """MD tag for an M-only alignment span (mismatches vs ref); callers
    pass aligned arrays only (soft clips already stripped)."""
    mism = np.flatnonzero(ref_slice != read)
    out = []
    last = 0
    for m in mism:
        out.append(str(m - last))
        out.append("ACGT"[ref_slice[m]])
        last = m + 1
    out.append(str(len(read) - last))
    return "".join(out)


def make_wgs(
    path: str,
    n_reads: int,
    read_len: int = 100,
    seed: int = 0,
    n_contigs: int = 4,
    contig_len: int = 800_000,
    known_sites_out: str | None = None,
    indel_every: int = 2_000,
    snp_every: int = 900,
    error_rate: float = 0.004,
    dup_frac: float = 0.10,
    clip_frac: float = 0.05,
    unmapped_frac: float = 0.01,
    trimmed_frac: float = 0.0,
    trimmed_min: int = 22,
    trimmed_max: int = 32,
) -> None:
    rng = np.random.default_rng(seed)
    contigs = [f"chr{i + 17}" for i in range(n_contigs)]
    refs = [rng.integers(0, 4, contig_len).astype(np.uint8)
            for _ in range(n_contigs)]

    # ---- planted variants ---------------------------------------------
    # indels: alternating insertion/deletion, lengths 1..8, every ~2 kb
    indels = []  # (contig, pos, is_ins, seq_codes or del_len)
    for c in range(n_contigs):
        p = int(rng.integers(500, indel_every))
        k = 0
        while p < contig_len - 2 * read_len:
            ln = int(rng.integers(1, 9))
            if k % 2 == 0:
                indels.append((c, p, True, rng.integers(0, 4, ln).astype(np.uint8)))
            else:
                indels.append((c, p, False, ln))
            p += int(rng.integers(indel_every // 2, indel_every * 3 // 2))
            k += 1
    # SNPs (known sites): alt differs from ref
    snps = []  # (contig, pos, alt_code)
    for c in range(n_contigs):
        p = int(rng.integers(100, snp_every))
        while p < contig_len - read_len:
            alt = (int(refs[c][p]) + int(rng.integers(1, 4))) % 4
            snps.append((c, p, alt))
            p += int(rng.integers(snp_every // 2, snp_every * 3 // 2))
    snp_by_contig = [
        {p: a for (c, p, a) in snps if c == ci} for ci in range(n_contigs)
    ]
    snp_pos_sorted = [np.array(sorted(d)) for d in snp_by_contig]
    indel_by_contig: list[dict] = [dict() for _ in range(n_contigs)]
    for (c, p, is_ins, payload) in indels:
        indel_by_contig[c][p] = (is_ins, payload)
    indel_pos_sorted = [np.array(sorted(d)) for d in indel_by_contig]

    if known_sites_out:
        with open(known_sites_out, "w") as fh:
            fh.write("##fileformat=VCFv4.2\n")
            for c, nm in enumerate(contigs):
                fh.write(f"##contig=<ID={nm},length={contig_len}>\n")
            fh.write("#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
            for (c, p, a) in sorted(snps):
                ref_b = "ACGT"[refs[c][p]]
                fh.write(
                    f"{contigs[c]}\t{p + 1}\t.\t{ref_b}\t{'ACGT'[a]}\t50\tPASS\t.\n"
                )

    # ---- pair sampling -------------------------------------------------
    n_pairs = n_reads // 2
    n_sites = max(1, int(n_pairs * (1.0 - dup_frac)))
    site_contig = rng.integers(0, n_contigs, n_sites)
    site_start = rng.integers(0, contig_len - 3 * read_len, n_sites)
    site_of_pair = np.concatenate(
        [np.arange(n_sites), rng.integers(0, n_sites, n_pairs - n_sites)]
    )
    rng.shuffle(site_of_pair)
    pr_contig = site_contig[site_of_pair]
    pr_start = site_start[site_of_pair]
    # isize is a property of the *fragment site*: PCR duplicates share
    # both 5' keys, which is what makes them duplicates
    site_isize = rng.integers(int(read_len * 2.2), int(read_len * 4), n_sites)
    isize = site_isize[site_of_pair]
    hap = rng.random(n_pairs) < 0.5  # haplotype carrying the variants
    # half the indel-spanning reads are emitted the way an indel-unaware
    # aligner would map them: all-M CIGAR, the indel smeared into tail
    # mismatches — the reads indel realignment exists to fix
    misalign = rng.random(n_reads) < 0.5
    # read-length variation: 88% full length, rest 60-99%
    lens = np.where(
        rng.random(n_reads) < 0.88,
        read_len,
        rng.integers(int(read_len * 0.6), read_len, n_reads),
    ).astype(np.int32)
    if trimmed_frac > 0.0:
        # trimmed-library shape (adapter-trimmed short-insert runs,
        # small-RNA 22-30 nt reads): a large fraction of reads carry a
        # small fraction of the instrument read length, while the
        # occasional untrimmed read keeps the window's Lmax at
        # read_len — the regime where dense [N, L] matrices carry
        # mostly padding and packed columns pay (docs/PERF.md)
        lens = np.where(
            rng.random(n_reads) < trimmed_frac,
            rng.integers(trimmed_min, trimmed_max + 1, n_reads),
            lens,
        ).astype(np.int32)
    clip = np.where(
        rng.random(n_reads) < clip_frac, rng.integers(3, 12, n_reads), 0
    ).astype(np.int32)
    unmapped_pair = rng.random(n_pairs) < unmapped_frac
    quals = _phred_profile(rng, n_reads, read_len)
    # quality-correlated errors: P(err) scaled by 10^(-q/10) shape
    err_p = error_rate * np.power(10.0, (25.0 - quals.astype(np.float32)) / 30.0)
    err_mask = rng.random((n_reads, read_len)) < err_p

    records = []  # (contig, start, line_parts...) for sorting

    def aln_start(ri, anchor, neg):
        """Aligned-span start from the fragment anchor, aligner-style:
        a forward read's POS advances past its leading soft clip; a
        reverse read anchors its 3'-most aligned base at the fragment
        end — so PCR duplicates of one fragment share 5'-clipped keys
        regardless of per-copy clipping/length (RichAlignmentRecord's
        fivePrimePosition contract, rich/RichAlignmentRecord.scala:104-126)."""
        L = int(lens[ri])
        cl = int(clip[ri])
        if not neg:
            return anchor + cl
        # reverse: sequencing starts at the fragment end and runs down;
        # the clipped (fragment-end-side) bases occupy [anchor-cl, anchor),
        # so the aligned span is [anchor - L, anchor - cl)
        return anchor - L

    def build_read(ri, c, start, hap_i, mate_start, first, neg, tlen):
        L = int(lens[ri])
        cl = int(clip[ri])
        aln_len = L - cl
        ref = refs[c]
        snpd = snp_by_contig[c]
        ipos = indel_pos_sorted[c]
        # nearest planted indel strictly inside the aligned span
        lo = np.searchsorted(ipos, start + 1)
        use_indel = None
        if hap_i and lo < len(ipos) and ipos[lo] < start + aln_len - 1:
            use_indel = int(ipos[lo])
        # build aligned sequence from the haplotype
        if use_indel is None:
            seq = ref[start : start + aln_len].copy()
            cig_mid = f"{aln_len}M"
            md_core_len = aln_len
            ref_span = aln_len
            md_parts = None
        else:
            a = use_indel - start  # M bases before the indel
            is_ins, payload = indel_by_contig[c][use_indel]
            if is_ins:
                ins = payload
                b = min(len(ins), aln_len - a - 1)
                rest = aln_len - a - b
                seq = np.concatenate(
                    [ref[start : start + a], ins[:b],
                     ref[start + a : start + a + rest]]
                )
                cig_mid = f"{a}M{b}I{rest}M"
                md_core_len = aln_len - b
                ref_span = a + rest
                md_parts = None
            else:
                dl = int(payload)
                rest = aln_len - a
                seq = np.concatenate(
                    [ref[start : start + a],
                     ref[start + a + dl : start + a + dl + rest]]
                )
                cig_mid = f"{a}M{dl}D{rest}M"
                ref_span = a + dl + rest
                md_parts = (a, ref[start + a : start + a + dl], rest)
                md_core_len = aln_len
        # apply het SNPs on this haplotype (they are real variants: they
        # mismatch the reference and land in MD, and BQSR should mask
        # them via the known-sites table); read offset approximates ref
        # offset on indel reads — MD stays exact either way, computed
        # from the final sequence below
        if hap_i:
            sp = snp_pos_sorted[c]
            for rp in sp[np.searchsorted(sp, start):
                         np.searchsorted(sp, start + len(seq))]:
                off = int(rp - start)
                if 0 <= off < len(seq):
                    seq[off] = snpd[int(rp)]
        # sequencing errors
        errs = np.flatnonzero(err_mask[ri][:len(seq)])
        for e in errs:
            seq[e] = (int(seq[e]) + int(1 + (ri + e) % 3)) % 4
        # MD vs the reference
        if use_indel is not None and misalign[ri]:
            # indel-unaware alignment: all-M, mismatch smear in the MD
            cig_mid = f"{len(seq)}M"
            md = _md_for(ref[start : start + len(seq)], seq)
        elif use_indel is None or md_parts is None:
            ref_slice = ref[start : start + len(seq)].copy()
            if cig_mid.endswith("M") and "I" in cig_mid:
                # insertion: MD covers the two M runs only
                a = int(cig_mid.split("M")[0])
                b = int(cig_mid.split("M")[1].split("I")[0])
                rd = np.concatenate([seq[:a], seq[a + b :]])
                rf = ref[start : start + len(rd)]
                md = _md_for(rf, rd)
            else:
                md = _md_for(ref_slice, seq)
        else:
            a, dseq, rest = md_parts
            md_a = _md_for(ref[start : start + a], seq[:a])
            md_r = _md_for(
                ref[start + a + len(dseq) : start + a + len(dseq) + rest],
                seq[a:],
            )
            md = f"{md_a}^{''.join('ACGT'[x] for x in dseq)}{md_r}"
        # soft clip: junk bases on the fragment-5' side of the read —
        # left (before POS) for forward reads, right for reverse reads
        if cl:
            junk = rng.integers(0, 4, cl).astype(np.uint8)
            if not neg:
                seq = np.concatenate([junk, seq])
                cigar = f"{cl}S{cig_mid}"
            else:
                seq = np.concatenate([seq, junk])
                cigar = f"{cig_mid}{cl}S"
        else:
            cigar = cig_mid
        if neg:
            flags = 0x1 | 0x10 | (0x40 if first else 0x80) | 0x2
        else:
            flags = 0x1 | 0x20 | (0x40 if first else 0x80) | 0x2
        q = quals[ri][: len(seq)]
        seq_s = _BASES[seq].tobytes().decode()
        q_s = (q + 33).tobytes().decode()
        # read group follows the *fragment*: PCR copies of one fragment
        # are in the same library, which is what makes them markable
        rg = "rg1" if site_of_pair[ri // 2] % 3 else "rg2"
        nm = len(np.flatnonzero(err_mask[ri][: len(seq) - cl]))
        return (
            c, start,
            f"\t{flags}\t{contigs[c]}\t{start + 1}\t60\t{cigar}\t=\t"
            f"{mate_start + 1}\t{tlen}\t{seq_s}\t{q_s}\tRG:Z:{rg}\t"
            f"MD:Z:{md}\tNM:i:{nm}",
        )

    for p in range(n_pairs):
        c = int(pr_contig[p])
        s1 = int(pr_start[p])
        name = f"r{p}"
        if unmapped_pair[p]:
            L = int(lens[2 * p])
            seq = _BASES[rng.integers(0, 4, L)].tobytes().decode()
            q = (quals[2 * p][:L] + 33).tobytes().decode()
            records.append((n_contigs, 0,
                            f"{name}\t77\t*\t0\t0\t*\t*\t0\t0\t{seq}\t{q}\tRG:Z:rg1"))
            L = int(lens[2 * p + 1])
            seq = _BASES[rng.integers(0, 4, L)].tobytes().decode()
            q = (quals[2 * p + 1][:L] + 33).tobytes().decode()
            records.append((n_contigs, 0,
                            f"{name}\t141\t*\t0\t0\t*\t*\t0\t0\t{seq}\t{q}\tRG:Z:rg1"))
            continue
        hp = bool(hap[p])
        tl = int(isize[p])
        frag_end = s1 + tl
        st1 = aln_start(2 * p, s1, False)
        st2 = aln_start(2 * p + 1, frag_end, True)
        c1, st1, tail1 = build_read(2 * p, c, st1, hp, st2, True, False, tl)
        c2, st2, tail2 = build_read(2 * p + 1, c, st2, hp, st1, False, True, -tl)
        records.append((c1, st1, name + tail1))
        records.append((c2, st2, name + tail2))

    records.sort(key=lambda r: (r[0], r[1]))
    with open(path, "w") as fh:
        fh.write("@HD\tVN:1.5\tSO:coordinate\n")
        for nm in contigs:
            fh.write(f"@SQ\tSN:{nm}\tLN:{contig_len}\n")
        fh.write("@RG\tID:rg1\tSM:sample\tLB:lib1\tPL:ILLUMINA\n")
        fh.write("@RG\tID:rg2\tSM:sample\tLB:lib2\tPL:ILLUMINA\n")
        buf = []
        for (_, _, line) in records:
            buf.append(line + "\n")
            if len(buf) >= 20000:
                fh.write("".join(buf))
                buf = []
        fh.write("".join(buf))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("path")
    ap.add_argument("--reads", type=int, default=1_000_000)
    ap.add_argument("--len", type=int, default=100, dest="read_len")
    ap.add_argument("--known-sites", default=None)
    ap.add_argument(
        "--trimmed-frac", type=float, default=0.0,
        help="fraction of reads hard-trimmed to a small-RNA-like "
             "length (default 0 = classic WGS length mix)",
    )
    ap.add_argument("--trimmed-min", type=int, default=22)
    ap.add_argument("--trimmed-max", type=int, default=32)
    args = ap.parse_args()
    make_wgs(args.path, args.reads, args.read_len,
             known_sites_out=args.known_sites,
             trimmed_frac=args.trimmed_frac,
             trimmed_min=args.trimmed_min, trimmed_max=args.trimmed_max)
    print(f"wrote {args.path}: {args.reads} reads x {args.read_len}bp"
          + (f" ({args.trimmed_frac:.0%} trimmed to "
             f"{args.trimmed_min}-{args.trimmed_max}bp)"
             if args.trimmed_frac else ""))
