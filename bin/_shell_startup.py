"""adam-tpu-shell preamble: the `import ADAMContext._` analog."""
import jax  # noqa: F401
import numpy as np  # noqa: F401

import adam_tpu  # noqa: F401
from adam_tpu.api.datasets import (  # noqa: F401
    AlignmentDataset,
    FeatureDataset,
    GenotypeDataset,
)
from adam_tpu.io.context import load_alignments  # noqa: F401

print(f"adam_tpu {adam_tpu.__version__} — devices: {jax.devices()}")
print("loaded: AlignmentDataset, GenotypeDataset, FeatureDataset, load_alignments")
