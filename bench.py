"""Benchmark: BASELINE.md configs on the real chip.

Primary metric — **end-to-end transform throughput**: a 1M-read,
WGS-shaped SAM file (multi-contig ~30x coverage, planted het indels and
known SNPs, quality-correlated errors, soft clips, PCR duplicates —
tools/make_wgs_sam.py) driven through the streamed flagship pipeline
(ingest || markdup summaries || BQSR observe/apply || realign sweeps ||
Parquet part writes; pipelines/streamed.py), the analog of the
reference's `transform -mark_duplicate_reads -recalibrate_base_qualities
-realign_indels` with dbSNP known sites
(adam-cli/.../Transform.scala:101-163 — BASELINE configs 2+3+4 fused).

`vs_baseline` is measured, not assumed: the **same input, same read
count, same streamed code** re-run in a subprocess forced onto the local
CPU backend (the stand-in for the reference's Spark-CPU executors — one
host, all cores), excluding one-time jit compiles on both sides via a
small warmup. Ratio of reads/sec is reported.

Secondary lines (one JSON object per line, driver reads line 1):
Smith-Waterman GCUPS (BASELINE metric 2), packed k-mer counting (metric
3 / config 1), per-stage wall split of the chip run, and the CPU
baseline's split.
"""

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Optional

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

N_READS = 1_000_000
READ_LEN = 100
_TAG = f"adam_tpu_bench_wgs_{N_READS}_{READ_LEN}_v3"


def _bench_cache_dir() -> str:
    """Per-user 0o700 input-cache directory.

    The old cache lived at fixed world-readable /tmp paths validated
    only by file size — any co-tenant could pre-create (or truncate) the
    path and the bench would silently measure their bytes.  The cache is
    now keyed by uid, created 0o700, ownership-checked, and every input
    is content-hash-validated against a manifest written at generation."""
    base = os.environ.get("ADAM_TPU_BENCH_CACHE") or os.path.join(
        tempfile.gettempdir(), f"adam_tpu_bench_u{os.getuid()}"
    )
    os.makedirs(base, mode=0o700, exist_ok=True)
    # ownership check BEFORE chmod: a co-tenant can pre-create the path
    # under sticky /tmp, and chmod-by-non-owner would raise a bare
    # PermissionError instead of this explanation
    st = os.stat(base)
    if st.st_uid != os.getuid():
        raise RuntimeError(
            f"bench cache {base} is owned by uid {st.st_uid}, not "
            f"{os.getuid()} — refusing to trust its contents"
        )
    os.chmod(base, 0o700)
    return base


_CACHE = _bench_cache_dir()
_SYNTH = os.path.join(_CACHE, _TAG + ".sam")
_KNOWN = os.path.join(_CACHE, _TAG + ".known.vcf")


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _inputs_valid(sam: str, known: str) -> bool:
    """True when both cached inputs match their generation-time hashes."""
    try:
        with open(sam + ".manifest.json") as fh:
            m = json.load(fh)
        return (
            _sha256(sam) == m["sam_sha256"]
            and _sha256(known) == m["known_sha256"]
        )
    except (OSError, ValueError, KeyError):
        return False


def _stamp_inputs(sam: str, known: str) -> None:
    with open(sam + ".manifest.json", "w") as fh:
        json.dump(
            {"sam_sha256": _sha256(sam), "known_sha256": _sha256(known)}, fh
        )


def _ensure_synth() -> None:
    if _inputs_valid(_SYNTH, _KNOWN):
        return
    from make_wgs_sam import make_wgs

    # 4 contigs x 800 kb at 1M x 100 bp ~= 31x coverage
    make_wgs(_SYNTH, N_READS, READ_LEN, known_sites_out=_KNOWN)
    _stamp_inputs(_SYNTH, _KNOWN)


def _known_table():
    from adam_tpu.api.datasets import GenotypeDataset
    from adam_tpu.io import context

    names = context.load_header(_SYNTH).seq_dict.names
    return GenotypeDataset.load(_KNOWN, contig_names=names).snp_table()


def _warmup_compiles(known) -> None:
    """Pay one-time jit compiles outside the timed run (both backends).

    Shape coverage matters more than read count: the streamed pipeline's
    device shapes are the pow2 window grid (window_reads=262144 -> grid
    262144; the 1M run's tail window rounds up to the same) and the
    fixed-CH sweep buckets — so the warm slice must span at least one
    FULL ingest window, or the timed run pays 20-40s per missed shape
    through the tunneled compile service (the round-3 lesson: a 40k-read
    warmup left ~2 minutes of compiles inside the timed region)."""
    from adam_tpu.pipelines.streamed import transform_streamed

    small = _SYNTH + ".warm270k.sam"
    if not os.path.exists(small):
        n = 0
        with open(_SYNTH) as src, open(small + ".tmp", "w") as dst:
            for line in src:
                dst.write(line)
                if not line.startswith("@"):
                    n += 1
                    if n >= 270_000:
                        break
        os.replace(small + ".tmp", small)
    # GEMM sweep tiers compile by (off, rt) only — warm them explicitly
    # so the 1M run cannot hit a shape the 270k slice's consensus-length
    # distribution happened to miss
    from adam_tpu.pipelines import realign as _realign

    _realign.warm_sweep_shapes()
    with tempfile.TemporaryDirectory() as td:
        # same device fan-out as the timed run: the warmup pays the
        # per-device prewarm compiles so the timed windows never do
        transform_streamed(
            small, os.path.join(td, "w.adam"), known_snps=known,
            devices=_DEVICES, partitioner=_PARTITIONER,
        )


def _matmul_probe(reps: int = 10, device=None) -> float:
    """Sustained bf16 matmul TFLOP/s right now — the granted-compute
    context recorded next to every timed window (the chip is
    time-sliced; a number without its window's grant is not evidence).
    ``device`` probes an explicit chip (the multi-chip per-device leg:
    time-sliced chips are NOT symmetric, so each pool device gets its
    own number)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        rng = np.random.default_rng(0)
        a = jnp.asarray(rng.standard_normal((4096, 4096)), jnp.bfloat16)
        bm = jnp.asarray(rng.standard_normal((4096, 4096)), jnp.bfloat16)
        if device is not None:
            a = jax.device_put(a, device)
            bm = jax.device_put(bm, device)

        @jax.jit
        def loop(a0):
            def body(i, c):
                return (c @ bm) * jnp.bfloat16(1e-3)
            return jax.lax.fori_loop(0, reps, body, a0)

        jax.block_until_ready(loop(a))
        t0 = time.perf_counter()
        jax.block_until_ready(loop(a + jnp.bfloat16(0)))
        dt = (time.perf_counter() - t0) / reps
        return round(2 * 4096 ** 3 / dt / 1e12, 1)
    except Exception:
        return float("nan")


#: --devices passthrough (None = all attached / ADAM_TPU_DEVICES).
_DEVICES = None

#: --partitioner passthrough (None = pool / ADAM_TPU_PARTITIONER).
_PARTITIONER = None

#: Zero-filled device leg: the CPU baseline records the SAME keys with
#: empty/zero values so round-over-round artifact diffs stay key-stable.
_NO_DEVICES = {
    "n_devices": 0,
    "devices_used": [],
    "per_device_probe_tflops": [],
    "partitioner": None,
    "error": None,
}


def _device_info(probe: bool = True) -> dict:
    """The chip leg's device context: how many chips the pool fans out
    over, which ones, and each one's same-window matmul probe (the
    chips are time-sliced independently — per-device grant skew is
    evidence, not noise).  On failure the zeros carry the error string
    (key-stable either way): a multi-chip run must never silently
    self-report as device-less."""
    try:
        import jax

        from adam_tpu.parallel.device_pool import resolve_device_count

        n = resolve_device_count(_DEVICES)
        # local_devices to match DevicePool: in a multi-process run
        # jax.devices() lists chips this host cannot probe
        devs = list(jax.local_devices())[:n]
        return {
            "n_devices": n,
            "devices_used": [int(getattr(d, "id", i))
                             for i, d in enumerate(devs)],
            "per_device_probe_tflops": [
                _matmul_probe(device=d) if probe else float("nan")
                for d in devs
            ],
            "partitioner": _PARTITIONER or "pool",
            "error": None,
        }
    except Exception as e:
        print(f"bench: device-info probe failed: {e!r}", file=sys.stderr)
        out = dict(_NO_DEVICES)
        out["error"] = repr(e)
        return out


def _denan(o):
    """NaN -> None through nested dicts/lists: the artifact lines must
    stay strict JSON (json.dumps would emit a bare NaN token)."""
    if isinstance(o, float) and o != o:
        return None
    if isinstance(o, dict):
        return {k: _denan(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_denan(v) for v in o]
    return o


def _host_load() -> float:
    try:
        return round(os.getloadavg()[0], 2)
    except OSError:
        return float("nan")


# Below this sustained matmul rate the granted slice is so starved that
# a timed window measures the scheduler, not the framework (quiet
# windows probe 6-22 TFLOP/s; the floor only rejects near-zero grants).
_PROBE_FLOOR_TFLOPS = float(
    os.environ.get("ADAM_TPU_BENCH_PROBE_FLOOR", "2.0")
)


def _probe_paced(max_retries: int = 3, wait_s: float = 15.0):
    """Matmul-probe the chip, waiting out starved slices.

    Returns (probe_tflops, skipped) where ``skipped`` lists the
    below-floor probes that were waited out — recorded in the artifact
    so a paced window is distinguishable from a lucky one.  After
    ``max_retries`` waits the window runs anyway (the bench must
    terminate on a permanently-starved slice) with its low probe
    recorded next to it."""
    skipped = []
    probe_tf = _matmul_probe()
    while (
        probe_tf == probe_tf  # not NaN: probe failure pacing is pointless
        and probe_tf < _PROBE_FLOOR_TFLOPS
        and len(skipped) < max_retries
    ):
        skipped.append(probe_tf)
        time.sleep(wait_s)
        probe_tf = _matmul_probe()
    return probe_tf, skipped


def _run_streamed(known, trials: int = 1, probe: bool = True) -> dict:
    """Best-of-``trials`` timed runs (the shared bench chip is
    time-sliced; identical runs vary several-x, so one sample measures
    the scheduler, not the framework).  Every trial records the
    same-window matmul-probe fraction and host 1-min load so the spread
    is attributable — and is paced by :func:`_probe_paced`, so a window
    doesn't start on a slice too starved to measure anything.  The
    returned dict carries best-trial stages plus the full per-window
    context under ``windows``/``spread`` and the best trial's telemetry
    snapshot under ``telemetry`` (key-stable: device-only counters/
    gauges are zero-filled on the CPU-baseline path instead of omitted,
    so round-over-round artifact diffs never churn on key sets)."""
    from adam_tpu.pipelines.streamed import transform_streamed
    from adam_tpu.utils import telemetry as tele

    best = None
    best_snap = None
    windows = []
    was_recording = tele.TRACE.recording
    for _ in range(max(1, trials)):
        if probe:
            probe_tf, skipped = _probe_paced()
        else:
            probe_tf, skipped = float("nan"), []
        load0 = _host_load()
        # per-trial telemetry window: reset + record so the snapshot
        # attached to the artifact is the BEST trial's, not a blur of
        # all trials
        tele.TRACE.reset()
        tele.TRACE.recording = True
        try:
            with tempfile.TemporaryDirectory() as td:
                stats = transform_streamed(
                    _SYNTH, os.path.join(td, "out.adam"), known_snps=known,
                    devices=_DEVICES, partitioner=_PARTITIONER,
                )
        finally:
            tele.TRACE.recording = was_recording
        snap = tele.key_stable_snapshot()
        w = {
            "total_s": round(stats["total_s"], 2),
            "probe_tflops_before": probe_tf,
            "host_load_before": load0,
            "host_load_after": _host_load(),
        }
        if skipped:
            w["probe_skipped"] = skipped
        windows.append(w)
        if best is None or stats["total_s"] < best["total_s"]:
            best = stats
            best_snap = snap
    totals = sorted(w["total_s"] for w in windows)
    best = dict(best)
    best["telemetry"] = best_snap
    # the analyzer's per-device utilization section (busy/idle/fetch/
    # replay attribution from the best trial's device_spans): every
    # bench artifact carries attribution built in, so the next real-
    # hardware multi-chip round starts from per-chip occupancy data
    # instead of a scalar wall.  Key-stable: the CPU baseline's empty
    # device_spans yields {"wall_s": ..., "devices": {}}.
    from adam_tpu.utils import analyzer

    best["utilization"] = analyzer.utilization_from_snapshot(best_snap)
    best["windows"] = windows
    best["spread"] = {
        "min_s": totals[0],
        "median_s": totals[len(totals) // 2],
        "max_s": totals[-1],
    }
    return best


def _cpu_baseline() -> dict:
    """Same pipeline, same 1M input, local CPU backend -> stats dict."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # do NOT share the persistent compile cache with tunneled-backend
    # runs: its "cpu" entries can be AOT results compiled by the remote
    # service for a different machine profile (+prefer-no-gather etc.) —
    # loading them silently de-optimizes the baseline's gather-heavy
    # kernels several-x (tests/conftest.py guards the same hazard)
    env["ADAM_TPU_NO_COMPILE_CACHE"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--cpu-child"],
        env=env, capture_output=True, text=True, timeout=3600,
    )
    for line in (proc.stdout or "").splitlines():
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"cpu child failed: {proc.stderr[-800:]}")


def _cpu_child() -> None:
    # belt for the parent's env braces: a hermetic CPU process must not
    # read tunneled-backend compile-cache entries (see _cpu_baseline)
    os.environ["ADAM_TPU_NO_COMPILE_CACHE"] = "1"
    try:
        import jax
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    known = _known_table()
    _warmup_compiles(known)
    # two trials: the forced-CPU child has no chip variance but DOES
    # share the single time-sliced host core — one sample measured
    # 8.2-25.4 s across round-5 windows, which alone swings vs_baseline
    # 0.89-2.85.  Two windows plus the recorded loadavg let the parent
    # pair quiet-against-quiet.
    # no matmul probe in the CPU child: a 4096^3 bf16 loop takes ~45s
    # on the single host core and would dwarf the measurement
    stats = _run_streamed(known, trials=2, probe=False)
    # key-stable device leg: zeros, not omission (see _NO_DEVICES)
    stats["devices"] = dict(_NO_DEVICES)
    print(json.dumps(stats))


def _sw_gcups() -> dict:
    """Smith-Waterman score-fill throughput (BASELINE metric 2).

    Chained-on-device reps (memoization/dispatch-proof), best of 3
    trials per backend (the shared chip is time-sliced; identical runs
    vary ~10x), both backends measured, winner labeled.  A bf16 matmul
    loop measured the same way gives the throttle context: the chip's
    achievable fraction of its 197-TFLOP/s peak *right now*, so the
    GCUPS number can be read against the hardware actually granted.
    """
    from adam_tpu.ops import smith_waterman as sw

    # each timed GCUPS window is bracketed by a matmul probe so the
    # number can be read against the compute actually granted in that
    # window (the chip is time-sliced): slice-normalized GCUPS =
    # gcups / (probe / 197 TFLOP/s peak).  If the kernel is bound by
    # the granted slice, normalized values are stable across windows
    # while raw values track the probe.
    PEAK_TFLOPS = 197.0
    windows = []
    out = {}
    for backend in ("pallas", "scan"):
        vals = []
        for _t in range(3):
            probe = _matmul_probe()
            try:
                g = round(sw.benchmark_gcups(backend=backend, trials=1), 2)
            except Exception:
                g = None
            if g is not None:
                frac = probe / PEAK_TFLOPS if probe == probe else None
                windows.append({
                    "backend": backend, "gcups": g,
                    "probe_tflops": probe,
                    "slice_normalized_gcups": (
                        round(g / frac, 1) if frac else None
                    ),
                })
                vals.append(g)
        out[backend] = max(vals) if vals else None
    ok = {k: v for k, v in out.items() if v}
    best = max(ok, key=ok.get) if ok else None
    norm = [
        w["slice_normalized_gcups"] for w in windows
        if w["slice_normalized_gcups"]
    ]
    best_vals = sorted(
        w["gcups"] for w in windows if w["backend"] == best
    ) if best else []
    return {
        "gcups": ok.get(best) if best else float("nan"),
        "gcups_median": (
            best_vals[len(best_vals) // 2] if best_vals else None
        ),
        "backend": best,
        "per_backend": out,
        "windows": windows,
        "slice_normalized_gcups_median": (
            sorted(norm)[len(norm) // 2] if norm else None
        ),
        "chip_matmul_tflops": max(
            (w["probe_tflops"] for w in windows
             if w["probe_tflops"] == w["probe_tflops"]), default=None
        ),
    }


def _kmers_per_sec() -> float:
    """count_kmers k=21 (BASELINE config 1 analog) on the bench file."""
    import jax
    import jax.numpy as jnp

    from adam_tpu.io import context
    from adam_tpu.ops import kmer

    ds = context.load_alignments(_SYNTH)
    b = ds.batch.to_device()
    args = (jnp.asarray(b.bases), jnp.asarray(b.lengths), jnp.asarray(b.valid))
    out = kmer.device_kmer_histogram(*args, 21)  # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = kmer.device_kmer_histogram(*args, 21)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    n_kmers = int(ds.batch.valid.sum()) * (READ_LEN - 21 + 1)
    return n_kmers / dt


def _scale_4m(budget_spent_s: float) -> Optional[dict]:
    """Opt-in 4M-read/125x scale config (PERF.md's coverage-depth check):
    one streamed run in a subprocess so peak RSS is the child's
    ru_maxrss.  Skipped when the bench has already spent its time budget
    or when generating the input would blow it; set
    ADAM_TPU_BENCH_SKIP_4M=1 to force-skip."""
    if os.environ.get("ADAM_TPU_BENCH_SKIP_4M"):
        return None
    tag = f"adam_tpu_bench_wgs_4000000_{READ_LEN}_v3"
    path = os.path.join(_CACHE, tag + ".sam")
    known = os.path.join(_CACHE, tag + ".known.vcf")
    cached = _inputs_valid(path, known)
    # budget: the driver gives the whole bench one wall budget; the 4M
    # leg (~1-3 min warm) only runs when the main legs left room, and
    # input generation (~10 min, one-time per user) only with plenty
    if budget_spent_s > (900 if cached else 420):
        return None
    if not cached:
        from make_wgs_sam import make_wgs

        make_wgs(path, 4_000_000, READ_LEN, known_sites_out=known)
        _stamp_inputs(path, known)
    child = r"""
import json, os, resource, sys, tempfile, time
sys.path.insert(0, %(repo)r)
from adam_tpu.api.datasets import GenotypeDataset
from adam_tpu.io import context
from adam_tpu.pipelines.streamed import transform_streamed
names = context.load_header(%(path)r).seq_dict.names
known = GenotypeDataset.load(%(known)r, contig_names=names).snp_table()
t0 = time.perf_counter()
with tempfile.TemporaryDirectory() as td:
    transform_streamed(%(path)r, os.path.join(td, "out.adam"),
                       known_snps=known, devices=%(devices)r)
wall = time.perf_counter() - t0
rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
print(json.dumps({"reads_4m_s": round(wall, 1),
                  "peak_rss_gb": round(rss, 2)}))
""" % {"repo": _REPO, "path": path, "known": known, "devices": _DEVICES}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, text=True,
            timeout=900,
        )
        for line in (proc.stdout or "").splitlines():
            if line.startswith("{"):
                return json.loads(line)
        print(
            f"4M scale leg failed (rc={proc.returncode}): "
            f"{(proc.stderr or '')[-400:]}",
            file=sys.stderr,
        )
    except Exception as e:
        print(f"4M scale leg failed: {e!r}", file=sys.stderr)
    return None


def _vs_baseline_windows(stages: dict, cpu_stats: dict) -> dict:
    """Chip-vs-CPU ratios from the recorded windows.

    Both legs run on the same time-shared host core minutes apart, so a
    single best-vs-best ratio swings 0.89-2.85 between runs.  Three
    estimates, most-robust first: ``median`` (median chip window over
    median CPU window — the headline), ``quiet`` (the chip window with
    the best granted slice against the least-loaded CPU window — the
    upper bound honest pairing allows), and ``best`` (the old
    best-vs-best, kept for continuity with r04/r05 artifacts)."""
    chip_w = stages.get("windows") or []
    cpu_w = cpu_stats.get("windows") or []
    if not chip_w or not cpu_w:
        return {}

    def _median(ts):
        # true median: even-length lists average the middle pair (with 2
        # CPU windows, index len//2 alone would pick the WORSE one and
        # flatter the chip)
        ts = sorted(ts)
        mid = len(ts) // 2
        return ts[mid] if len(ts) % 2 else (ts[mid - 1] + ts[mid]) / 2

    chip_t = sorted(w["total_s"] for w in chip_w)
    cpu_t = sorted(w["total_s"] for w in cpu_w)
    out = {
        "median": round(_median(cpu_t) / _median(chip_t), 2),
        "best": round(cpu_t[0] / chip_t[0], 2),
    }
    def _probe_of(w):
        p = w.get("probe_tflops_before")
        # NaN (failed probe) must sort as "no grant evidence", not
        # poison the tuple comparison into picking an arbitrary window
        return p if (p is not None and p == p) else 0.0

    quiet_chip = min(
        chip_w, key=lambda w: (-_probe_of(w), w["total_s"])
    )
    def _load_of(w):
        ld = w.get("host_load_before")
        # non-finite load (unreadable loadavg) must never win "quietest"
        return ld if (ld is not None and ld == ld) else float("inf")

    quiet_cpu = min(
        cpu_w, key=lambda w: (_load_of(w), w["total_s"])
    )
    out["quiet"] = round(quiet_cpu["total_s"] / quiet_chip["total_s"], 2)
    out["quiet_pairing"] = {
        "chip_probe_tflops": quiet_chip.get("probe_tflops_before"),
        "chip_total_s": quiet_chip["total_s"],
        "cpu_load": quiet_cpu.get("host_load_before"),
        "cpu_total_s": quiet_cpu["total_s"],
    }
    return out


def main() -> None:
    t_bench0 = time.perf_counter()
    _ensure_synth()
    known = _known_table()
    _warmup_compiles(known)
    stages = _run_streamed(known, trials=3)
    rps = stages["n_reads"] / stages["total_s"]
    median_s = stages.get("spread", {}).get("median_s") or stages["total_s"]
    rps_median = stages["n_reads"] / median_s

    try:
        cpu_stats = _cpu_baseline()
        cpu_rps = cpu_stats["n_reads"] / cpu_stats["total_s"]
        pairing = _vs_baseline_windows(stages, cpu_stats)
        # headline ratio: median window against median window (the old
        # best-vs-best headline is pairing["best"])
        vs = pairing.get("median") or (
            rps / cpu_rps if cpu_rps > 0 else None
        )
    except Exception:
        cpu_stats, cpu_rps, vs, pairing = {}, float("nan"), None, {}

    try:
        sw_info = _sw_gcups()
    except Exception:
        sw_info = {"gcups": float("nan"), "backend": None}
    try:
        kps = _kmers_per_sec()
    except Exception:
        kps = float("nan")

    print(
        json.dumps(
            _denan({
                "metric": "transform_e2e_reads_per_sec_per_chip",
                "value": round(rps, 1),
                "median": round(rps_median, 1),
                "unit": (
                    "reads/sec (1M-read WGS-shaped SAM at ~31x: streamed "
                    "ingest+markdup+BQSR(known-sites)+realign+parquet "
                    "parts, one chip; value = best of 3 probe-paced "
                    "windows, median = median window — the chip slice is "
                    "time-shared; CPU baseline = same input/code on host "
                    "cores, 2 windows, vs_baseline = median-vs-median)"
                ),
                "vs_baseline": round(vs, 2) if vs is not None else None,
            })
        )
    )
    # per-config reads/sec derived from the fused run's stage split
    # (BASELINE configs 2-4; config 1 is the kmers line).  "derived"
    # because each config's wall = its stages + the shared ingest cost;
    # attribution is approximate where stages fuse (observe_s carries
    # the candidate split, realign_s the realigned part's observation —
    # each a few percent of its wall).
    n = stages["n_reads"]

    def _cfg(*keys):
        t = stages.get("ingest_pass_s", 0) + sum(stages.get(k, 0) for k in keys)
        return round(n / t, 1) if t > 0 else None

    configs = {
        "cfg2_markdup_derived_rps": _cfg("resolve_s"),
        # the device backend records dispatch/fetch as disjoint rows
        # next to the host apply share — all of them are BQSR wall
        "cfg3_bqsr_known_sites_derived_rps": _cfg(
            "observe_s", "obs_merge_fetch_s", "solve_s", "apply_split_s",
            "apply_device_dispatch_s", "apply_device_fetch_s",
        ),
        "cfg4_realign_derived_rps": _cfg("realign_s"),
    }
    scale4m = _scale_4m(time.perf_counter() - t_bench0)
    # per-device probes AFTER the timed windows (probing 8 chips inside
    # the measurement region would perturb it); chips are time-sliced
    # independently, so the per-device spread is the skew context for
    # the pool's round-robin dispatch
    dev_info = _device_info()
    # per-kernel microbench (utils/kernelbench.py) — isolates the
    # observe/pack/apply/fused_bc inner loops per backend so a Pallas
    # port can't regress one of them invisibly inside the e2e number;
    # bench-diff flattens rows to kernels.<kernel>.<backend>.g<g>x<gl>.*
    try:
        from adam_tpu.utils.kernelbench import run_kernelbench

        kernels_doc = run_kernelbench(iters=3)
    except Exception as e:
        kernels_doc = {"error": f"{type(e).__name__}: {e}"}
    print(
        json.dumps(
            _denan({
                "metric": "secondary",
                "kernels": kernels_doc,
                "devices": {
                    "chip": dev_info,
                    "cpu_baseline": cpu_stats.get("devices")
                    or dict(_NO_DEVICES),
                },
                "sw": sw_info,
                "kmers_per_sec": round(kps, 1),
                "cpu_baseline_reads_per_sec": round(cpu_rps, 1),
                "vs_baseline_windows": pairing or None,
                **configs,
                **(scale4m or {}),
                "chip_windows": stages.get("windows"),
                "chip_total_spread_s": stages.get("spread"),
                # the CPU baseline runs minutes after the chip windows on
                # a time-shared host core: its load context must be in
                # the artifact or the ratio can't be read honestly
                "cpu_windows": cpu_stats.get("windows"),
                "chip_stages_s": {
                    k: round(v, 2)
                    for k, v in stages.items()
                    if k.endswith("_s") and isinstance(v, float)
                },
                "cpu_stages_s": {
                    k: round(v, 2)
                    for k, v in cpu_stats.items()
                    if k.endswith("_s") and isinstance(v, float)
                },
                # best-trial telemetry snapshots (spans/counters/gauges;
                # utils/telemetry.py) — per-stage trajectories for
                # future rounds.  Both legs are key-stable: the CPU
                # baseline zero-fills device-only metrics instead of
                # omitting them.
                "telemetry": {
                    "chip": stages.get("telemetry"),
                    "cpu_baseline": cpu_stats.get("telemetry"),
                },
                # per-device busy/idle/fetch/replay attribution of the
                # best trial (utils/analyzer.py) — the multi-chip
                # artifact's occupancy evidence, {} on the CPU leg
                "utilization": {
                    "chip": stages.get("utilization"),
                    "cpu_baseline": cpu_stats.get("utilization"),
                },
            })
        )
    )


def _parse_devices_arg(argv: list) -> None:
    """Consume ``--devices N`` / ``--devices=N`` from argv (sets the
    module-level passthrough).  A missing or non-integer value is a
    usage error, not a crash — and never a silent fall-through to
    all-attached, which would mislabel the artifact."""
    global _DEVICES
    for i, a in enumerate(list(argv)):
        if a == "--devices" or a.startswith("--devices="):
            if a == "--devices":
                val = argv[i + 1] if i + 1 < len(argv) else None
                span = 2
            else:
                val = a.split("=", 1)[1]
                span = 1
            try:
                _DEVICES = int(val)
            except (TypeError, ValueError):
                sys.exit(f"bench.py: --devices needs an integer (got {val!r})")
            del argv[i : i + span]
            return


def _parse_partitioner_arg(argv: list) -> None:
    """Consume ``--partitioner {pool,mesh}`` (the streamed execution
    mode passthrough); invalid values are a usage error so the
    artifact's ``partitioner`` key never mislabels the run."""
    global _PARTITIONER
    for i, a in enumerate(list(argv)):
        if a == "--partitioner" or a.startswith("--partitioner="):
            if a == "--partitioner":
                val = argv[i + 1] if i + 1 < len(argv) else None
                span = 2
            else:
                val = a.split("=", 1)[1]
                span = 1
            if val not in ("pool", "mesh"):
                sys.exit(
                    f"bench.py: --partitioner must be pool or mesh "
                    f"(got {val!r})"
                )
            _PARTITIONER = val
            del argv[i : i + span]
            return


if __name__ == "__main__":
    argv = sys.argv[1:]
    _parse_devices_arg(argv)
    _parse_partitioner_arg(argv)
    if argv and argv[0] == "--cpu-child":
        _cpu_child()
        sys.exit(0)
    main()
