"""Benchmark: BASELINE.md configs on the real chip.

Primary metric — **end-to-end transform throughput**: a 1M-read SAM file
driven through the full flagship pipeline (ingest -> mark duplicates ->
BQSR -> indel realignment -> Parquet save), the analog of the reference's
`transform -mark_duplicate_reads -recalibrate_base_qualities
-realign_indels` (adam-cli/.../Transform.scala:101-163).  This times the
whole system: host codecs, columnar batch construction, device kernels,
and device<->host transfers.

`vs_baseline` is measured, not assumed: the same pipeline is re-run in a
subprocess forced onto the local CPU backend (the stand-in for the
reference's Spark-CPU executors — one host, all cores, same vectorized
code), on a 100k-read slice, and the ratio of reads/sec is reported.

Secondary lines (also printed, one JSON object per line, driver reads
line 1): Smith-Waterman wavefront GCUPS (scan backend; see
ops/smith_waterman._use_pallas for the measured backend choice)
(BASELINE.md metric 2), packed k-mer counting throughput (metric 3,
the count_kmers k=21 config), and the stage split of the e2e run.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

N_READS = 1_000_000
READ_LEN = 100
_SYNTH = os.path.join(
    tempfile.gettempdir(), f"adam_tpu_bench_synth_{N_READS}_{READ_LEN}.sam"
)


def _ensure_synth(path: str, n_reads: int) -> None:
    if os.path.exists(path) and os.path.getsize(path) > n_reads * 100:
        return
    from tools.make_synth_sam import make_sam

    make_sam(path, n_reads, READ_LEN)


def _pipeline(path: str, out_dir: str) -> dict:
    """Run the flagship pipeline once; return stage timings + read count."""
    from adam_tpu.io import context

    stages = {}
    t0 = time.perf_counter()
    ds = context.load_alignments(path)
    stages["ingest_s"] = time.perf_counter() - t0
    n = int(ds.batch.valid.sum())

    t = time.perf_counter()
    ds = ds.mark_duplicates()
    stages["markdup_s"] = time.perf_counter() - t

    t = time.perf_counter()
    ds = ds.recalibrate_base_qualities()
    stages["bqsr_s"] = time.perf_counter() - t

    t = time.perf_counter()
    ds = ds.realign_indels()
    stages["realign_s"] = time.perf_counter() - t

    t = time.perf_counter()
    ds.save(os.path.join(out_dir, "out.adam"))
    stages["save_s"] = time.perf_counter() - t

    stages["total_s"] = time.perf_counter() - t0
    stages["n_reads"] = n
    return stages


def _cpu_baseline_rps() -> float:
    """Same pipeline on the local CPU backend, 100k-read slice -> reads/s."""
    cpu_path = _SYNTH.replace(".sam", "_100k.sam")
    _ensure_synth(cpu_path, 100_000)
    env = dict(os.environ)
    env["ADAM_TPU_BENCH_CPU_CHILD"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--cpu-child", cpu_path],
        env=env, capture_output=True, text=True, timeout=3600,
    )
    for line in (proc.stdout or "").splitlines():
        if line.startswith("{"):
            return float(json.loads(line)["reads_per_sec"])
    return float("nan")


def _cpu_child(path: str) -> None:
    # drop the axon PJRT factory so "cpu" really is the local CPU
    try:
        import jax
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    with tempfile.TemporaryDirectory() as td:
        stages = _pipeline(path, td)
    print(json.dumps({"reads_per_sec": stages["n_reads"] / stages["total_s"]}))


def _sw_gcups() -> float:
    """Smith-Waterman wavefront fill throughput, 4096 pairs of 127x127.

    The repetition loop runs ON DEVICE (fori_loop inside one jit) with a
    data-dependency chain between fills — per-call dispatch through a
    tunneled chip costs 10-25 ms and the axon client memoizes repeated
    identical executions, so naive host-side rep loops measure neither.
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from adam_tpu.ops import smith_waterman as sw

    args = (1.0, -0.333, -0.5, -0.5)
    B, lx, ly = 4096, 127, 127
    reps = 10

    @functools.partial(jax.jit, static_argnames=())
    def bench_fill(xc, xl, yc, yl):
        def body(i, carry):
            x, acc = carry
            m, bs, bd = sw._sw_fill_scan_best.__wrapped__(
                x, xl, yc, yl, *args, lx, ly
            )
            x = x + (bd[0:1, 0:1] % 1).astype(x.dtype)
            return (x, acc + bs[0, 0])

        return jax.lax.fori_loop(0, reps, body, (xc, jnp.float32(0)))[1]

    rng = np.random.default_rng(0)
    xc = jnp.asarray(rng.integers(0, 4, (B, lx)), jnp.int32)
    yc = jnp.asarray(rng.integers(0, 4, (B, ly)), jnp.int32)
    xl = jnp.full((B,), lx, jnp.int32)
    yl = jnp.full((B,), ly, jnp.int32)
    acc = bench_fill(xc, xl, yc, yl)
    jax.block_until_ready(acc)
    t0 = time.perf_counter()
    acc = bench_fill(xc + 1 - 1, xl, yc, yl)
    float(acc)  # force full sync
    dt = (time.perf_counter() - t0) / reps
    return B * lx * ly / dt / 1e9


def _kmers_per_sec(path: str) -> float:
    """count_kmers k=21 (BASELINE config 1 analog) on the bench file."""
    import jax
    import jax.numpy as jnp

    from adam_tpu.io import context
    from adam_tpu.ops import kmer

    ds = context.load_alignments(path)
    b = ds.batch.to_device()
    args = (jnp.asarray(b.bases), jnp.asarray(b.lengths), jnp.asarray(b.valid))
    out = kmer.device_kmer_histogram(*args, 21)  # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = kmer.device_kmer_histogram(*args, 21)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    n_kmers = int(ds.batch.valid.sum()) * (READ_LEN - 21 + 1)
    return n_kmers / dt


def main() -> None:
    _ensure_synth(_SYNTH, N_READS)

    with tempfile.TemporaryDirectory() as td:
        stages = _pipeline(_SYNTH, td)
    rps = stages["n_reads"] / stages["total_s"]

    try:
        cpu_rps = _cpu_baseline_rps()
        vs = rps / cpu_rps if cpu_rps == cpu_rps and cpu_rps > 0 else None
    except Exception:
        cpu_rps, vs = float("nan"), None

    try:
        gcups = _sw_gcups()
    except Exception:
        gcups = float("nan")
    try:
        kps = _kmers_per_sec(_SYNTH)
    except Exception:
        kps = float("nan")

    print(
        json.dumps(
            {
                "metric": "transform_e2e_reads_per_sec_per_chip",
                "value": round(rps, 1),
                "unit": (
                    "reads/sec (1M-read SAM: ingest+markdup+BQSR+realign+"
                    "parquet save, one chip)"
                ),
                "vs_baseline": round(vs, 2) if vs is not None else None,
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "secondary",
                "sw_wavefront_gcups": round(gcups, 2),
                "kmers_per_sec": round(kps, 1),
                "cpu_baseline_reads_per_sec": round(cpu_rps, 1),
                "stages_s": {
                    k: round(v, 2)
                    for k, v in stages.items()
                    if k.endswith("_s")
                },
            }
        )
    )


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--cpu-child":
        _cpu_child(sys.argv[2])
        sys.exit(0)
    main()
