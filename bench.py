"""Benchmark: reads/sec/chip on the fused transform step.

Times the flagship device kernel (BQSR observe + recalibrate + duplicate
-marking keys + flagstat, one jit region — the hot per-partition work of
the reference's `transform` pipeline) on synthetic 100 bp reads, on
whatever accelerator JAX provides (the real TPU chip under the driver).

`vs_baseline` compares against a single-host vectorized numpy
implementation of the same observe+recalibrate math (the stand-in for
the reference's Spark-CPU executor loop; numpy is a *stronger* CPU
baseline than per-record JVM objects, so the ratio is conservative
relative to BASELINE.md's >=20x-over-Spark north star).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

import numpy as np


def _numpy_baseline(batch, residue_ok, is_mm, n_rg, lmax, repeats=3):
    """Vectorized single-host numpy version of observe + recalibrate."""
    from adam_tpu.formats import schema

    bases = np.asarray(batch.bases)
    quals = np.asarray(batch.quals).astype(np.int64)
    lengths = np.asarray(batch.lengths)
    flags = np.asarray(batch.flags)
    rg = np.asarray(batch.read_group_idx)
    n, L = bases.shape
    err = 10.0 ** (-np.arange(256) / 10.0)

    def run_once():
        # cycles
        rev = (flags & 0x10) != 0
        second = ((flags & 0x1) != 0) & ((flags & 0x80) != 0)
        initial = np.where(rev, np.where(second, -lengths, lengths),
                           np.where(second, -1, 1))
        inc = np.where(rev, np.where(second, 1, -1), np.where(second, -1, 1))
        cycles = initial[:, None] + inc[:, None] * np.arange(L)[None, :]
        # dinucs
        comp = schema.BASE_COMPLEMENT
        prev_f = np.pad(bases[:, :-1], ((0, 0), (1, 0)), constant_values=4)
        next_b = np.pad(bases[:, 1:], ((0, 0), (0, 1)), constant_values=4)
        cur = np.where(rev[:, None], comp[bases], bases)
        prev = np.where(rev[:, None], comp[next_b], prev_f)
        i = np.arange(L)[None, :]
        first = np.where(rev[:, None], i == lengths[:, None] - 1, i == 0)
        ok = (i < lengths[:, None]) & ~first & (cur < 4) & (prev < 4)
        dinucs = np.where(ok, prev.astype(np.int64) * 4 + cur, 16)
        # observe
        n_cyc = 2 * L + 1
        key = (((np.clip(rg, 0, n_rg - 1)[:, None] * 94 + np.clip(quals, 0, 93))
                * n_cyc + cycles + L) * 17 + dinucs)
        inc_mask = residue_ok
        size = n_rg * 94 * n_cyc * 17
        total = np.bincount(key[inc_mask].ravel(), minlength=size)
        mism = np.bincount(key[inc_mask & is_mm].ravel(), minlength=size)
        total = total.reshape(n_rg, 94, n_cyc, 17)
        mism = mism.reshape(n_rg, 94, n_cyc, 17)
        # recalibrate
        g_t = total.sum(axis=(1, 2, 3))
        g_m = mism.sum(axis=(1, 2, 3))
        g_exp = (err[np.arange(94)][None, :] * total.sum(axis=(2, 3))).sum(axis=1)
        q_t = total.sum(axis=(2, 3))
        q_m = mism.sum(axis=(2, 3))
        c_t = total.sum(axis=3)
        c_m = mism.sum(axis=3)
        d_t = total.sum(axis=2)
        d_m = mism.sum(axis=2)
        rgc = np.clip(rg, 0, n_rg - 1)[:, None] * np.ones((1, L), np.int64)
        q = np.clip(quals, 0, 93)
        rlp = np.log(err[q])

        def emp(t, m):
            return np.log((1.0 + m) / (2.0 + t))

        gt = g_t[rgc]
        gd = np.where(gt > 0, emp(gt, g_m[rgc]) - np.log(g_exp[rgc] / np.maximum(gt, 1)), 0.0)
        qt = q_t[rgc, q]
        qp = (gt > 0) & (qt > 0)
        off1 = rlp + gd
        qd = np.where(qp, emp(qt, q_m[rgc, q]) - off1, 0.0)
        off2 = off1 + qd
        ct = c_t[rgc, q, cycles + L]
        cd = np.where(qp & (ct > 0), emp(ct, c_m[rgc, q, cycles + L]) - off2, 0.0)
        dt = d_t[rgc, q, dinucs]
        dd = np.where(qp & (dt > 0), emp(dt, d_m[rgc, q, dinucs]) - off2, 0.0)
        logp = np.clip(rlp + gd + qd + cd + dd, np.log(err[50]), 0.0)
        return np.floor(-10.0 * logp / np.log(10.0) + 0.5)

    run_once()
    t0 = time.perf_counter()
    for _ in range(repeats):
        run_once()
    return (time.perf_counter() - t0) / repeats


def main():
    import jax
    import jax.numpy as jnp

    from adam_tpu.pipelines.transform_step import (
        synthetic_batch,
        synthetic_masks,
        transform_step,
    )

    n_reads = 65_536
    read_len = 100
    n_rg = 2
    batch = synthetic_batch(n_reads=n_reads, read_len=read_len)
    residue_ok, is_mm = synthetic_masks(batch)
    dev_batch = batch.to_device()
    res_d, mm_d = jnp.asarray(residue_ok), jnp.asarray(is_mm)

    # warmup/compile
    out, aux = transform_step(dev_batch, res_d, mm_d, n_rg, read_len)
    jax.block_until_ready(out.quals)

    repeats = 10
    t0 = time.perf_counter()
    for _ in range(repeats):
        out, aux = transform_step(dev_batch, res_d, mm_d, n_rg, read_len)
    jax.block_until_ready(out.quals)
    device_time = (time.perf_counter() - t0) / repeats
    reads_per_sec = n_reads / device_time

    baseline_time = _numpy_baseline(batch, residue_ok, is_mm, n_rg, read_len)
    baseline_rps = n_reads / baseline_time

    print(
        json.dumps(
            {
                "metric": "transform_step_reads_per_sec_per_chip",
                "value": round(reads_per_sec, 1),
                "unit": "reads/sec (100bp, BQSR observe+recalibrate+markdup keys+flagstat)",
                "vs_baseline": round(reads_per_sec / baseline_rps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
