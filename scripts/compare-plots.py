#!/usr/bin/env python
"""Compare two alignment outputs: mapq/baseq histograms, duplicate-flag
mismatches, position concordance.

Analog of the reference's ``adam-scripts/R/plots.R``, which charts
mapq/base-quality distributions, duplicate-marking mismatches and
position agreement between two pipeline runs (e.g. ADAM vs GATK).  Here
the same four comparisons read any two outputs this framework can load
(SAM/BAM/ADAM Parquet) and print binned tables; pass ``--png PREFIX``
to also render bar charts when matplotlib is available.

Usage: compare-plots.py <A> <B> [--png PREFIX]
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def histo(values, splits):
    """Counts binned as plots.R's splitby: <=s0, (s0,s1], ..., >last.

    np.histogram bins are left-closed, so nudge the finite edges up by
    0.5 (values are integers) to get the right-closed buckets the
    labels describe — mapq 0 must land in '< 1', not '1 - 10'."""
    edges = [-np.inf] + [s + 0.5 for s in splits] + [np.inf]
    counts, _ = np.histogram(values, bins=np.array(edges, float))
    names = [f"< {splits[0] + 1}"]
    for prev, cur in zip(splits, splits[1:]):
        names.append(str(cur) if prev + 1 == cur else f"{prev + 1} - {cur}")
    names.append(f"> {splits[-1]}")
    return names, counts


def main(argv):
    if len(argv) < 3:
        sys.stderr.write("Usage: compare-plots.py <A> <B> [--png PREFIX]\n")
        return 1
    png = None
    if "--png" in argv:
        png = argv[argv.index("--png") + 1]

    from adam_tpu.io import context

    out = {}
    sides = {}
    for label, path in (("A", argv[1]), ("B", argv[2])):
        ds = context.load_alignments(path)
        b = ds.batch.to_numpy()
        valid = np.asarray(b.valid)
        sides[label] = (ds, b, valid)
        mapq = np.asarray(b.mapq)[valid]
        inlen = (
            np.arange(b.lmax)[None, :]
            < np.asarray(b.lengths)[valid][:, None]
        )
        quals = np.asarray(b.quals)[valid][inlen]
        out[label] = (mapq, quals)

    mq_splits = [0, 10, 20, 30, 40, 50, 60]
    bq_splits = [2, 10, 20, 30, 40]
    tables = {}
    for metric, idx, splits in (
        ("mapq", 0, mq_splits), ("baseq", 1, bq_splits)
    ):
        print(f"== {metric} ==")
        print("bin\tA\tB")
        na, ca = histo(out["A"][idx], splits)
        _nb, cb = histo(out["B"][idx], splits)
        for name, a, bcount in zip(na, ca, cb):
            print(f"{name}\t{a}\t{bcount}")
        tables[metric] = (na, ca, cb)

    # duplicate-flag mismatch + position concordance, keyed by read name
    def keyed(label):
        ds, b, valid = sides[label]
        names = ds.sidecar.names
        flags = np.asarray(b.flags)
        start = np.asarray(b.start)
        return {
            (names[i], int(flags[i]) & 0xC0): (
                bool(flags[i] & 0x400), int(start[i])
            )
            for i in np.flatnonzero(valid)
        }

    ka, kb = keyed("A"), keyed("B")
    common = set(ka) & set(kb)
    dup_mismatch = sum(1 for k in common if ka[k][0] != kb[k][0])
    pos_mismatch = sum(1 for k in common if ka[k][1] != kb[k][1])
    print("== concordance ==")
    print(f"common reads\t{len(common)}")
    print(f"only in A\t{len(ka) - len(common)}")
    print(f"only in B\t{len(kb) - len(common)}")
    print(f"duplicate-flag mismatches\t{dup_mismatch}")
    print(f"position mismatches\t{pos_mismatch}")

    if png:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            for metric, (names, ca, cb) in tables.items():
                fig, ax = plt.subplots(figsize=(7, 4))
                x = np.arange(len(names))
                ax.bar(x - 0.2, ca, 0.4, label="A")
                ax.bar(x + 0.2, cb, 0.4, label="B")
                ax.set_xticks(x, names, rotation=45)
                ax.set_title(metric)
                ax.legend()
                fig.tight_layout()
                fig.savefig(f"{png}-{metric}.png", dpi=120)
            print(f"wrote {png}-{{mapq,baseq}}.png")
        except ImportError:
            sys.stderr.write("matplotlib unavailable; tables only\n")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
