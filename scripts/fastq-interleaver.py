#!/usr/bin/env python
"""Interleave two paired FASTQ files record-by-record.

Analog of the reference's ``scripts/fastq-interleaver.py``: reads one
4-line record from each file in turn, emitting the mate whose name sorts
first consistently (the reference determines the order once from the
first record pair and keeps it), and fails loudly on a truncated record
or mismatched file lengths.

The columnar framework reads the result with ``-force_load_ifastq`` /
``io/fastq.py``'s interleaved codec; this standalone script exists for
parity with the reference's tooling and for preparing inputs outside
the framework.
"""

import sys


def get_one(f):
    first = f.readline()
    if not first:
        return None
    rec = [first]
    for _ in range(3):
        line = f.readline()
        if not line:
            raise SystemExit("File ended in the middle of a fastq record")
        rec.append(line)
    return rec


def main(argv):
    if len(argv) != 3:
        sys.stderr.write("Usage: fastq-interleaver <fastq_1> <fastq_2>\n")
        return 1
    with open(argv[1]) as f1, open(argv[2]) as f2:
        file1_first = False
        order_determined = False
        while True:
            r1 = get_one(f1)
            r2 = get_one(f2)
            if r1 is None and r2 is None:
                return 0
            if r1 is None or r2 is None:
                raise SystemExit("Input files have different record counts")
            if not order_determined:
                file1_first = r1[0] <= r2[0]
                order_determined = True
            first, second = (r1, r2) if file1_first else (r2, r1)
            sys.stdout.write("".join(first))
            sys.stdout.write("".join(second))


if __name__ == "__main__":
    sys.exit(main(sys.argv) or 0)
